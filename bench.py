"""Benchmark: SSB Q1.1-shaped scan-aggregation on the TPU query engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...breakdown}.

Config #2 from BASELINE.md: flat-lineorder range-filter + SUM, no index.
  SELECT SUM(lo_extendedprice * lo_discount) FROM ssb
  WHERE lo_orderdate BETWEEN 19940101 AND 19940131
    AND lo_discount BETWEEN 4 AND 6 AND lo_quantity BETWEEN 26 AND 35

value = device rows-scanned/sec (one chip) with PIPELINE_DEPTH queries in
flight — the serving-path number (ref Pinot is built for 100k+ QPS; the
engine dispatches outside its staging lock so concurrent round trips
overlap on the async device queue). The breakdown records sequential p50
latency, the measured host<->device link round trip (a trivial x+1 sync —
on a tunneled single-chip setup this floor dominates sequential latency
and its jitter, which is what moved rounds 1-3: 96-123ms/query against a
79-165ms measured RT band), per-phase host times, and effective HBM GB/s
vs the v5e ~819 GB/s roofline.

vs_baseline = speedup over the numpy reference executor at max_threads=8
(honest multi-core host baseline; the 1-thread number is also recorded).

Segments are built once into ./bench_data (git-ignored) and reloaded on
later runs; columns stay HBM-resident across queries (the segment cache of
SURVEY.md §7.5), so steady-state timing reflects the scan path, not I/O.
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_SEGMENTS = 16
DOCS_PER_SEGMENT = 8_000_000
PIPELINE_DEPTH = 16
DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_data")
QUERY = ("SELECT SUM(lo_extendedprice * lo_discount), COUNT(*) FROM ssb "
         "WHERE lo_orderdate BETWEEN 19940101 AND 19940131 "
         "AND lo_discount BETWEEN 4 AND 6 AND lo_quantity BETWEEN 26 AND 35")
#: bytes the kernel reads per row with cardinality-aware id staging:
#: i8 discount ids + i16 orderdate ids + i8 quantity ids + 2 f32 values
#: (the engine reports the ACTUAL staged bytes at runtime; this is the
#: fallback for the derived GB/s when introspection fails)
BYTES_PER_ROW = 1 + 2 + 1 + 4 + 4


def measure_device_kernel(ex, segments, iters: int = 20):
    """Direct steady-state kernel timing (device only — no link, no host
    assembly): the number VERDICT r4 asked for (device_time_ms) plus the
    actual staged bytes so GB/s is measured, not modeled."""
    import jax

    from pinot_tpu.ops import kernels as _k
    from pinot_tpu.query.context import QueryContext
    eng = ex.tpu_engine
    ctx = QueryContext.from_sql(QUERY)
    with eng._engine_lock:
        plan_info = eng._plan(segments, ctx)
        if plan_info is None:
            return None, None
        plan, _slots = plan_info
        cols, params, num_docs, _S, D, G = eng._stage(segments, ctx, plan)
        kern = _k.compiled_kernel(plan)
    jax.block_until_ready(kern(cols, params, num_docs, D=D, G=G))
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = kern(cols, params, num_docs, D=D, G=G)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    nbytes = sum(v.nbytes for v in cols.values())
    return dt, nbytes


def build_data():
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator

    schema = Schema("ssb", [
        FieldSpec("lo_orderdate", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_discount", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_quantity", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_extendedprice", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("ssb", TableType.OFFLINE)
    # high-cardinality measure stays raw (no dictionary); random ints are
    # incompressible, so skip chunk compression for build/load speed
    tc.indexing.no_dictionary_columns = ["lo_extendedprice"]
    tc.indexing.compression = "PASS_THROUGH"
    creator = SegmentCreator(tc, schema)
    dates = np.array([y * 10000 + m * 100 + d
                      for y in range(1992, 1999)
                      for m in range(1, 13) for d in range(1, 29)],
                     dtype=np.int32)
    for i in range(NUM_SEGMENTS):
        out = os.path.join(DATA_DIR, f"seg_{i}")
        if os.path.exists(os.path.join(out, "metadata.json")):
            continue
        rng = np.random.default_rng(1000 + i)
        n = DOCS_PER_SEGMENT
        cols = {
            "lo_orderdate": dates[rng.integers(0, len(dates), n)],
            "lo_discount": rng.integers(0, 11, n).astype(np.int32),
            "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
            "lo_extendedprice": rng.integers(90_000, 10_000_000, n).astype(np.int32),
        }
        creator.build(cols, out, f"ssb_{i}")


def load():
    from pinot_tpu.segment.loader import load_segment
    return [load_segment(os.path.join(DATA_DIR, f"seg_{i}"))
            for i in range(NUM_SEGMENTS)]


def measure_link_rt_ms(n: int = 5) -> float:
    """Round trip of a trivial device sync — the latency floor every
    sequential query pays on this host<->device link."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((8,), jnp.float32)
    np.asarray(f(x))
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(f(x))
        ts.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(ts)


def phase_breakdown(engine, segments, n: int = 20) -> dict:
    """Host-side per-phase times (ms) for the steady-state query."""
    from pinot_tpu.query.context import QueryContext

    def t(fn, n=n):
        fn()
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        return (time.perf_counter() - t0) / n * 1e3, out

    parse_ms, ctx = t(lambda: QueryContext.from_sql(QUERY))
    plan_ms, plan_info = t(lambda: engine._plan(segments, ctx))
    plan = plan_info[0]
    stage_ms, _ = t(lambda: engine._stage(segments, ctx, plan))
    return {"parse_ms": round(parse_ms, 3), "plan_ms": round(plan_ms, 3),
            "stage_steady_ms": round(stage_ms, 3)}


def time_sequential(ex, n_iters: int, warmup: int = 2):
    for _ in range(warmup):
        resp = ex.execute(QUERY)
    lat = []
    for _ in range(n_iters):
        t0 = time.perf_counter()
        resp = ex.execute(QUERY)
        lat.append(time.perf_counter() - t0)
    return lat, resp


def time_pipelined(ex, depth: int, n_iters: int):
    with ThreadPoolExecutor(depth) as pool:
        list(pool.map(lambda _: ex.execute(QUERY), range(depth)))  # warm
        t0 = time.perf_counter()
        list(pool.map(lambda _: ex.execute(QUERY), range(n_iters)))
        dt = (time.perf_counter() - t0) / n_iters
    return dt


def deadline_overhead_main():
    """--deadline-overhead: cost of the reliability layer's cooperative
    deadline checks on the UNCACHED scatter path (ISSUE 3 satellite).

    Measures p50 over the host executor with and without a registered
    cancel-checker (the exact closure the server threads into the
    per-segment loop), on many small segments so the per-segment check
    count (not one big scan) dominates the comparison, plus the full
    broker scatter p50 through a real MiniCluster for context. Asserts
    the checks add <2% p50 and writes BENCH_reliability.json."""
    import statistics as stats
    import tempfile

    import numpy as np

    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.accounting import ResourceAccountant

    num_segments, docs = 64, 20_000
    query = ("SELECT SUM(v), COUNT(*) FROM t "
             "WHERE k BETWEEN 100 AND 800 OPTION(skipCache=true)")
    schema = Schema("t", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    creator = SegmentCreator(TableConfig("t", TableType.OFFLINE), schema)
    tmp = tempfile.mkdtemp(prefix="bench_reliability_")
    segments = []
    for i in range(num_segments):
        rng = np.random.default_rng(i)
        d = os.path.join(tmp, f"seg_{i}")
        creator.build({"k": rng.integers(0, 1000, docs).astype(np.int32),
                       "v": rng.integers(0, 100, docs).astype(np.int32)},
                      d, f"t_{i}")
        segments.append(load_segment(d))

    accountant = ResourceAccountant()
    accountant.begin_query("bench", timeout_s=3600.0)

    ex_base = QueryExecutor(segments, use_tpu=False)
    ex_checked = QueryExecutor(segments, use_tpu=False,
                               cancel_check=accountant.checker("bench"))

    def one(ex):
        t0 = time.perf_counter()
        ex.execute(query)
        return (time.perf_counter() - t0) * 1e3

    # strictly interleaved base/checked samples: ambient drift (thermal,
    # noisy neighbors) hits both configs equally instead of masquerading
    # as check overhead across two separated runs
    for _ in range(3):
        one(ex_base), one(ex_checked)
    base_lat, checked_lat = [], []
    for _ in range(40):
        base_lat.append(one(ex_base))
        checked_lat.append(one(ex_checked))
    base = stats.median(base_lat)
    checked = stats.median(checked_lat)
    overhead_pct = (checked - base) / base * 100.0

    # full scatter path through a real broker/server round trip
    from pinot_tpu.cluster.mini import MiniCluster
    cluster = MiniCluster(num_servers=2)
    cluster.start()
    cluster.add_table("t")
    for i, seg in enumerate(segments):
        cluster.add_segment("t", seg, server_idx=i % 2)
    try:
        for _ in range(3):
            cluster.query(query)
        lat = []
        for _ in range(20):
            t0 = time.perf_counter()
            resp = cluster.query(query)
            lat.append((time.perf_counter() - t0) * 1e3)
        assert not resp.exceptions, resp.exceptions
        scatter_p50 = stats.median(lat)
    finally:
        cluster.stop()

    out = {
        "metric": "deadline_check_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%",
        "p50_base_ms": round(base, 3),
        "p50_checked_ms": round(checked, 3),
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "scatter_p50_ms": round(scatter_p50, 2),
        "asserted_max_pct": 2.0,
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_reliability.json"), "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    # epsilon absorbs scheduler noise on sub-ms medians; the check is a
    # dict-get + time compare per segment, far below either bound
    assert overhead_pct < 2.0 or (checked - base) < 0.5, \
        f"deadline checks cost {overhead_pct:.2f}% p50 (>{2.0}%)"


def trace_overhead_main(smoke: bool = False):
    """--trace-overhead [--smoke]: tracing-off must stay free (ISSUE 12).

    Two paired A/B legs over identical MiniClusters in one process,
    strictly interleaved so ambient drift hits both sides equally:

    * off leg — pinot.trace.enabled=false (NO trace machinery: the
      pre-PR request path) vs the default config with trace=false
      (shadow span collection + tail capture armed). Asserts the shadow
      machinery adds <2% p50.
    * on leg — trace=false vs trace=true on the default cluster: the
      full stitched cross-process tree (server trees shipped in every
      response, per-op scopes, store retention). Reported and asserted
      BOUNDED (<25% or <5ms absolute) — trace=true is a debugging mode,
      not the hot path, but it must stay usable under load.

    Writes BENCH_tracing.json; the smoke leg is tier-1 via
    tests/test_tracing.py.
    """
    import statistics as stats
    import tempfile

    import numpy as np

    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    num_segments = 8 if smoke else 32
    docs = 5_000 if smoke else 20_000
    iters = 16 if smoke else 40
    query = ("SELECT SUM(v), COUNT(*) FROM t "
             "WHERE k BETWEEN 100 AND 800 OPTION(skipCache=true)")
    traced_query = ("SET trace = true; " + query)

    schema = Schema("t", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    creator = SegmentCreator(TableConfig("t", TableType.OFFLINE), schema)
    tmp = tempfile.mkdtemp(prefix="bench_tracing_")
    segments = []
    for i in range(num_segments):
        rng = np.random.default_rng(i)
        d = os.path.join(tmp, f"seg_{i}")
        creator.build({"k": rng.integers(0, 1000, docs).astype(np.int32),
                       "v": rng.integers(0, 100, docs).astype(np.int32)},
                      d, f"t_{i}")
        segments.append(load_segment(d))

    def make_cluster(cfg):
        c = MiniCluster(num_servers=2, config=cfg)
        c.start()
        c.add_table("t")
        for i, seg in enumerate(segments):
            c.add_segment("t", seg, server_idx=i % 2)
        return c

    off_cfg = PinotConfiguration(
        overrides={"pinot.trace.enabled": False})
    on_cfg = PinotConfiguration()  # defaults: shadow tracing armed
    c_off = make_cluster(off_cfg)
    c_on = make_cluster(on_cfg)

    def one(c, q):
        t0 = time.perf_counter()
        resp = c.query(q)
        assert not resp.exceptions, resp.exceptions
        return (time.perf_counter() - t0) * 1e3

    def paired_pct(run_a, run_b, n):
        """Median of per-pair ratios, back-to-back A/B per iteration
        with ALTERNATING order (a,b / b,a) — ambient drift cancels per
        pair and a fixed-order bias (the second call riding the first's
        cache/scheduler warmth) cancels across pairs."""
        ratios, deltas, a_lat, b_lat = [], [], [], []
        for i in range(n):
            if i % 2 == 0:
                a = run_a()
                b = run_b()
            else:
                b = run_b()
                a = run_a()
            a_lat.append(a)
            b_lat.append(b)
            ratios.append(b / a)
            deltas.append(b - a)
        return ((stats.median(ratios) - 1.0) * 100.0,
                stats.median(deltas),
                stats.median(a_lat), stats.median(b_lat))

    try:
        # warm both clusters (JIT, routing, sockets, thread pools)
        for _ in range(8):
            one(c_off, query), one(c_on, query)
        # A/A noise floor: the same cluster against itself — whatever
        # "overhead" this shows is measurement noise, and the real
        # assertions must clear it, not just the 2% target. BOTH
        # clusters stay equally exercised during the floor pass: an
        # idle cluster cools (scheduler/socket warmth) and would bias
        # leg 1 against it.
        noise_pct, _, _, _ = paired_pct(
            lambda: one(c_off, query),
            lambda: (one(c_on, query), one(c_off, query))[1], iters)
        noise_pct = abs(noise_pct)

        # -- leg 1: machinery off vs shadow-on, trace=false both sides
        shadow_pct, shadow_delta_ms, p50_off, p50_shadow = paired_pct(
            lambda: one(c_off, query), lambda: one(c_on, query), iters)

        # -- leg 2: trace=false vs trace=true on the shadow cluster
        for _ in range(3):
            one(c_on, traced_query)
        traced_pct, traced_delta_ms, p50_plain, p50_traced = paired_pct(
            lambda: one(c_on, query), lambda: one(c_on, traced_query),
            iters)
        resp = c_on.query(traced_query)
        assert resp.trace is not None, "trace=true returned no traceInfo"
        assert any(ch.get("operator") == "ServerScatter"
                   for ch in resp.trace.get("children", ())), resp.trace
    finally:
        c_off.stop()
        c_on.stop()

    out = {
        "metric": "tracing_off_overhead_pct",
        "value": round(shadow_pct, 3),
        "unit": "%",
        "p50_off_ms": round(p50_off, 3),
        "p50_shadow_ms": round(p50_shadow, 3),
        "p50_traced_ms": round(p50_traced, 3),
        "traced_overhead_pct": round(traced_pct, 3),
        "shadow_paired_delta_ms": round(shadow_delta_ms, 3),
        "traced_paired_delta_ms": round(traced_delta_ms, 3),
        "aa_noise_floor_pct": round(noise_pct, 3),
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "iters": iters,
        "smoke": smoke,
        "asserted_max_pct": 2.0,
        "asserted_traced_max_pct": 25.0,
    }
    if not smoke:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_tracing.json"), "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))
    # the A/A floor + absolute epsilon absorb shared-box scheduler noise
    # (paired ratios already cancel drift; what's left is jitter) — the
    # shadow cost itself is a handful of dict/list ops per query, far
    # below either bound. The traced bound is deliberately loose (debug
    # mode): it exists to catch accidental O(rows) work on the span path.
    # the smoke leg runs inside tier-1 on whatever box CI gives it, and
    # a loaded 2-core host shows A/A floors of 3-8% — it simply cannot
    # resolve a 2% delta (the floor itself is one noisy draw). The
    # STRICT <2% bar belongs to the full run (the committed
    # BENCH_tracing.json); smoke asserts the qualitative contract (the
    # stitched trace exists, tracing-off is not MULTI-ms/tens-of-percent
    # more expensive) so a real O(ms) regression on the shadow path
    # still fails tier-1 without the noise flaking it
    if smoke:
        shadow_bound = max(25.0, 2.0 * noise_pct + 5.0)
        shadow_eps_ms = max(2.0, 0.10 * p50_off)
    else:
        shadow_bound = max(2.0, noise_pct + 1.0)
        shadow_eps_ms = 0.5
    assert shadow_pct < shadow_bound or shadow_delta_ms < shadow_eps_ms, \
        (f"shadow tracing costs {shadow_pct:.2f}% p50 "
         f"({shadow_delta_ms:.3f}ms paired; bound {shadow_bound:.2f}%, "
         f"A/A floor {noise_pct:.2f}%)")
    traced_eps_ms = max(5.0, 0.25 * p50_plain) if smoke else 5.0
    assert traced_pct < max(25.0, 2.0 * noise_pct + 25.0) \
        or traced_delta_ms < traced_eps_ms, \
        f"trace=true costs {traced_pct:.2f}% p50 (>25%)"


def concurrency_main(smoke: bool = False):
    """--concurrency [--smoke]: A/B the dispatch pipeline (ISSUE 4).

    Closed-loop N-client driver over fingerprint-equal queries with
    per-client literals (the dashboard-fleet case), run twice IN THE
    SAME PROCESS: dispatch.mode=serialized (the pre-PR inline dispatch:
    collective-bearing kernels hold the process-global lock across
    dispatch + fetch) vs pipelined (dispatch ring + shared-plan
    micro-batching + staging/compute overlap). Records aggregate QPS,
    single-client p50, batch-size stats, and the steady-state retrace
    count; asserts the acceptance bars (full mode) and writes
    BENCH_dispatch.json. --smoke shrinks data + durations to fit the
    tier-1 timeout.

    On CPU hosts the bench forces the 8-virtual-device mesh the server
    runs under in CI — that is exactly the configuration where the old
    path serializes every kernel process-wide, which is the bottleneck
    this pipeline removes."""
    import statistics as stats
    import tempfile
    import threading

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")

    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.ops import dispatch as dispatch_mod
    from pinot_tpu.ops import kernels
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    # the serving regime the pipeline targets (and the TPU reality:
    # BENCH_r05 device time ~9.8ms vs ~119ms serialized query): per-query
    # DEVICE COMPUTE is small next to per-launch overhead, so the win is
    # amortizing launches, not adding FLOPs. Small segments put the CPU
    # stand-in in the same regime; scale up on real accelerators.
    num_segments = 4
    docs = 2_000
    clients = 8
    duration_s = 1.2 if smoke else 6.0
    p50_iters = 12 if smoke else 40

    schema = Schema("ssb", [
        FieldSpec("lo_orderdate", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_discount", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_quantity", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_extendedprice", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("ssb", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["lo_extendedprice"]
    tc.indexing.compression = "PASS_THROUGH"
    creator = SegmentCreator(tc, schema)
    tmp = tempfile.mkdtemp(prefix="bench_dispatch_")
    dates = np.array([y * 10000 + m * 100 + d
                      for y in range(1992, 1999)
                      for m in range(1, 13) for d in range(1, 29)],
                     dtype=np.int32)
    segments = []
    for i in range(num_segments):
        rng = np.random.default_rng(3000 + i)
        out = os.path.join(tmp, f"seg_{i}")
        creator.build({
            "lo_orderdate": dates[rng.integers(0, len(dates), docs)],
            "lo_discount": rng.integers(0, 11, docs).astype(np.int32),
            "lo_quantity": rng.integers(1, 51, docs).astype(np.int32),
            "lo_extendedprice": rng.integers(
                90_000, 10_000_000, docs).astype(np.int32),
        }, out, f"ssb_{i}")
        segments.append(load_segment(out))
    total_rows = sum(s.num_docs for s in segments)

    # the dashboard fleet: one plan fingerprint, per-client literals
    queries = [
        ("SELECT SUM(lo_extendedprice * lo_discount), COUNT(*) FROM ssb "
         "WHERE lo_orderdate BETWEEN 19940101 AND 19940131 "
         f"AND lo_discount BETWEEN {a} AND {a + 2} "
         "AND lo_quantity BETWEEN 26 AND 35")
        for a in range(clients)]

    def warm_batch_buckets(engine):
        """Deterministically trace every batched (plan, bucket) shape the
        measured window can produce, so steady-state retraces are a real
        regression signal, not warmup noise."""
        prep = engine._prepare_agg(
            segments, QueryContext.from_sql(queries[0]))
        assert prep is not None, "bench query must stage on-device"
        launch = prep[3]
        guard = dispatch_mod._CPU_COLLECTIVE_LOCK if launch.collective \
            else None
        b = 2
        while b <= max(2, dispatch_mod._pow2(clients)):
            kern = dispatch_mod.compiled_batched_kernel(launch.plan, b)
            plist = (launch.params,) * b
            if guard is not None:
                with guard:
                    jax.block_until_ready(kern(
                        launch.cols, plist, launch.num_docs,
                        D=launch.D, G=launch.G))
            else:
                jax.block_until_ready(kern(
                    launch.cols, plist, launch.num_docs,
                    D=launch.D, G=launch.G))
            b *= 2

    # clients drive the SERVER-SIDE execution path
    # (QueryExecutor.execute_context, what query_server.py calls per
    # request) with pre-parsed contexts: SQL parse + broker reduce are
    # per-request Python that the GIL serializes in this reproduction
    # regardless of dispatch — a JVM/C++ server does them on independent
    # cores, so including them would just measure the GIL, not the
    # pipeline under test
    def make_mode(mode):
        engine = TpuOperatorExecutor(config=PinotConfiguration(
            overrides={"pinot.server.dispatch.mode": mode}))
        ex = QueryExecutor(segments, use_tpu=True, engine=engine)
        ctxs = [QueryContext.from_sql(q) for q in queries]
        for c in ctxs:  # stage + compile the single-kernel path
            results, _stats = ex.execute_context(c)
            assert results
        return engine, ex, ctxs

    eng_ser, ex_ser, ctxs_ser = make_mode("serialized")
    eng_pipe, ex_pipe, ctxs_pipe = make_mode("pipelined")
    warm_batch_buckets(eng_pipe)

    # single-client p50: STRICTLY INTERLEAVED A/B samples, so ambient
    # drift (thermal, noisy neighbors, allocator state) hits both modes
    # equally instead of masquerading as pipeline overhead
    def one(ex, ctxs, i):
        t0 = time.perf_counter()
        ex.execute_context(ctxs[i % len(ctxs)])
        return (time.perf_counter() - t0) * 1e3

    for i in range(4):
        one(ex_ser, ctxs_ser, i), one(ex_pipe, ctxs_pipe, i)
    lat_ser, lat_pipe = [], []
    for i in range(p50_iters):
        # alternate which mode goes first within the pair: a fixed order
        # hands the second call a systematically warmer CPU
        if i % 2 == 0:
            lat_ser.append(one(ex_ser, ctxs_ser, i))
            lat_pipe.append(one(ex_pipe, ctxs_pipe, i))
        else:
            lat_pipe.append(one(ex_pipe, ctxs_pipe, i))
            lat_ser.append(one(ex_ser, ctxs_ser, i))

    def closed_window(ex, ctxs, window_s):
        counts = [0] * clients
        stop_at = time.perf_counter() + window_s

        def client(ci):
            j = 0
            while time.perf_counter() < stop_at:
                ex.execute_context(ctxs[(ci + j) % len(ctxs)])
                counts[ci] += 1
                j += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts), time.perf_counter() - t0

    # ALTERNATING closed-loop windows (ser/pipe/ser/pipe...): one long
    # window per mode would compare two different moments of a shared
    # box; interleaved short windows hand ambient drift to both modes
    reg = eng_pipe._dispatcher._metrics
    batch_t0 = reg.timer("dispatch_batch_size")
    batch_c0, batch_max0 = batch_t0.count, batch_t0.max_ms
    traces0 = kernels.trace_count()
    rounds = 2 if smoke else 6
    ser_n = ser_wall = pipe_n = pipe_wall = 0.0
    for _r in range(rounds):
        n, w = closed_window(ex_ser, ctxs_ser, duration_s / rounds)
        ser_n += n
        ser_wall += w
        n, w = closed_window(ex_pipe, ctxs_pipe, duration_s / rounds)
        pipe_n += n
        pipe_wall += w
    batch_t = reg.timer("dispatch_batch_size")
    serialized = {"qps": ser_n / ser_wall, "queries_completed": int(ser_n)}
    pipelined = {
        "qps": pipe_n / pipe_wall,
        "queries_completed": int(pipe_n),
        "retraces_steady": kernels.trace_count() - traces0,
        "batch_launches": batch_t.count - batch_c0,
        "batch_size_max": max(batch_t.max_ms, batch_max0),
    }
    serialized["p50_single_ms"] = round(stats.median(lat_ser), 2)
    pipelined["p50_single_ms"] = round(stats.median(lat_pipe), 2)
    # PAIRED median delta: sample i of each mode ran back-to-back, so
    # the per-pair difference cancels ambient drift (cpu frequency,
    # noisy neighbors) that makes the two independent medians swing
    # ±10% on a small shared box
    paired_delta_ms = stats.median(
        p - s for s, p in zip(lat_ser, lat_pipe))
    speedup = pipelined["qps"] / max(serialized["qps"], 1e-9)
    p50_delta_pct = paired_delta_ms / serialized["p50_single_ms"] * 100.0
    out = {
        "metric": "concurrent_dispatch_qps_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "clients": clients,
        "duration_s": duration_s,
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "total_rows": total_rows,
        "smoke": smoke,
        "serialized": {k: (round(v, 2) if isinstance(v, float) else v)
                       for k, v in serialized.items()},
        "pipelined": {k: (round(v, 2) if isinstance(v, float) else v)
                      for k, v in pipelined.items()},
        "p50_single_delta_pct": round(p50_delta_pct, 2),
        "p50_paired_delta_ms": round(paired_delta_ms, 3),
        "asserted": {"min_speedup": 2.0, "max_p50_regress_pct": 5.0,
                     "max_steady_retraces": 0},
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_dispatch.json"), "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    assert pipelined["retraces_steady"] == 0, \
        f"steady-state retraces: {pipelined['retraces_steady']}"
    if not smoke:
        assert speedup >= 2.0, f"pipelined speedup {speedup:.2f}x < 2x"
        # epsilon absorbs scheduler noise on few-ms medians (the lone-
        # query fast path makes the two single-client code paths nearly
        # identical; any real regression shows up far above this)
        assert p50_delta_pct < 5.0 or paired_delta_ms < 0.5, \
            f"single-client p50 regressed {p50_delta_pct:.1f}% " \
            f"({paired_delta_ms:.2f}ms paired)"


def residency_main(smoke: bool = False):
    """--residency [--smoke]: A/B the HBM residency tier (ISSUE 6).

    Paired cold-vs-resident driver IN THE SAME PROCESS, interleaved like
    --concurrency so ambient drift hits both arms:

      * resident — one engine kept warm across queries: columns stay in
        device HBM, blocks assemble from the block cache, params are
        plan-keyed. The steady state must ship ZERO host->device bytes
        and compile NOTHING (both odometers asserted).
      * cold — an engine whose caches (device AND host rows) are dropped
        before every query: the full re-ship a fresh replica pays —
        segment decode, pad, stack, link transfer — which is exactly
        what the residency tier deletes from the steady state.
      * cold/legacy — the same cold path with residency disabled (host
        stack + whole-block upload): guards the cold path against
        regression from the per-row upload + on-device assembly.

    Writes BENCH_residency.json. Kernels compile once up front; cold
    timing measures the data path, not XLA. --smoke shrinks data and
    skips the ratio bars.

    Ratio bar: >=5x warm-resident over cold on a real accelerator, where
    cold pays host decode + the ~100ms link per query and resident pays
    ~one link round trip (BENCH_r05: device 13 GRows/s vs 1.07 GRows/s
    sequential end-to-end). On a CPU-ONLY stand-in there is no link to
    delete — the structural ceiling is (staging + kernel) / kernel with
    both sides running on the same cores — so the enforced floor drops
    to 3x (residency still deletes the entire staging phase, which is
    everything deletable there); the steady-state zero-transfer /
    zero-retrace bars and the cold-regression bar assert everywhere."""
    import statistics as stats
    import tempfile

    import jax

    from pinot_tpu.ops import kernels, residency
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.utils.config import PinotConfiguration

    if smoke:
        from pinot_tpu.models import (DataType, FieldSpec, FieldType,
                                      Schema, TableConfig, TableType)
        from pinot_tpu.segment.creator import SegmentCreator
        from pinot_tpu.segment.loader import load_segment
        schema = Schema("ssb", [
            FieldSpec("lo_orderdate", DataType.INT, FieldType.DIMENSION),
            FieldSpec("lo_discount", DataType.INT, FieldType.DIMENSION),
            FieldSpec("lo_quantity", DataType.INT, FieldType.DIMENSION),
            FieldSpec("lo_extendedprice", DataType.INT, FieldType.METRIC),
        ])
        tc = TableConfig("ssb", TableType.OFFLINE)
        tc.indexing.no_dictionary_columns = ["lo_extendedprice"]
        tc.indexing.compression = "PASS_THROUGH"
        creator = SegmentCreator(tc, schema)
        tmp = tempfile.mkdtemp(prefix="bench_residency_")
        dates = np.array([y * 10000 + m * 100 + d
                          for y in range(1992, 1999)
                          for m in range(1, 13) for d in range(1, 29)],
                         dtype=np.int32)
        segments = []
        for i in range(4):
            rng = np.random.default_rng(5000 + i)
            n = 50_000
            out = os.path.join(tmp, f"seg_{i}")
            creator.build({
                "lo_orderdate": dates[rng.integers(0, len(dates), n)],
                "lo_discount": rng.integers(0, 11, n).astype(np.int32),
                "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
                "lo_extendedprice": rng.integers(
                    90_000, 10_000_000, n).astype(np.int32),
            }, out, f"ssb_{i}")
            segments.append(load_segment(out))
    else:
        os.makedirs(DATA_DIR, exist_ok=True)
        build_data()
        segments = load()
    total_rows = sum(s.num_docs for s in segments)

    def make(resident_enabled: bool):
        eng = TpuOperatorExecutor(config=PinotConfiguration(overrides={
            "pinot.server.hbm.resident.enabled": resident_enabled}))
        return eng, QueryExecutor(segments, use_tpu=True, engine=eng)

    eng_res, ex_res = make(True)        # stays warm: the resident arm
    eng_cr, ex_cr = make(True)          # flushed per query: cold arm
    eng_cl, ex_cl = make(False)         # flushed per query: cold legacy

    # compile + first staging for every engine (cold timing must measure
    # the data path, not XLA)
    want = ex_res.execute(QUERY).rows
    for eng, ex in ((eng_cr, ex_cr), (eng_cl, ex_cl)):
        got = ex.execute(QUERY).rows
        assert got == want, f"arm disagreement: {got} vs {want}"
        eng.drop_caches(host=True)

    def one(ex):
        t0 = time.perf_counter()
        resp = ex.execute(QUERY)
        dt = time.perf_counter() - t0
        assert resp.rows == want
        return dt * 1e3

    def cold_one(eng, ex):
        eng.drop_caches(host=True)
        dt = one(ex)
        # drop again AFTER timing: a cold arm must not sit on gigabytes
        # of staged blocks while the resident windows run — that memory
        # pressure would bleed into the other arm's samples
        eng.drop_caches(host=True)
        return dt

    rounds = 2 if smoke else 4
    res_iters = 8 if smoke else 20
    cold_iters = 2 if smoke else 4
    lat_res, lat_cold, lat_cold_legacy = [], [], []
    res_transfers = res_traces = 0
    for r in range(rounds):
        # resident window first; cold flushes touch OTHER engines, so
        # the resident engine's steady state spans the whole run
        b0, t0 = residency.transfer_bytes(), kernels.trace_count()
        for _ in range(res_iters):
            lat_res.append(one(ex_res))
        res_transfers += residency.transfer_bytes() - b0
        res_traces += kernels.trace_count() - t0
        for i in range(cold_iters):
            # alternate which cold arm goes first within the pair
            if (r + i) % 2 == 0:
                lat_cold.append(cold_one(eng_cr, ex_cr))
                lat_cold_legacy.append(cold_one(eng_cl, ex_cl))
            else:
                lat_cold_legacy.append(cold_one(eng_cl, ex_cl))
                lat_cold.append(cold_one(eng_cr, ex_cr))

    p50_res = stats.median(lat_res)
    p50_cold = stats.median(lat_cold)
    p50_cold_legacy = stats.median(lat_cold_legacy)
    resident_rate = total_rows / (p50_res / 1e3)
    cold_rate = total_rows / (p50_cold / 1e3)
    speedup = resident_rate / max(cold_rate, 1e-9)
    # paired delta: sample i of both cold arms ran back-to-back
    cold_paired_delta_ms = stats.median(
        c - l for c, l in zip(lat_cold, lat_cold_legacy))
    cold_regress_pct = cold_paired_delta_ms / p50_cold_legacy * 100.0
    device_like = jax.default_backend() != "cpu"
    min_speedup = 5.0 if device_like else 3.0
    out = {
        "metric": "hbm_residency_warm_vs_cold_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "num_segments": len(segments),
        "total_rows": total_rows,
        "smoke": smoke,
        "link_rt_ms": round(measure_link_rt_ms(), 2),
        "resident": {
            "p50_ms": round(p50_res, 2),
            "rows_per_sec": round(resident_rate),
            "transfer_bytes_steady": res_transfers,
            "retraces_steady": res_traces,
            "hbm_resident_rows": len(eng_res._residency),
            "hbm_resident_bytes": eng_res._residency.bytes,
        },
        "cold": {"p50_ms": round(p50_cold, 2),
                 "rows_per_sec": round(cold_rate)},
        "cold_legacy": {"p50_ms": round(p50_cold_legacy, 2),
                        "rows_per_sec": round(
                            total_rows / (p50_cold_legacy / 1e3))},
        "cold_paired_delta_ms": round(cold_paired_delta_ms, 3),
        "cold_regress_pct": round(cold_regress_pct, 2),
        "backend": jax.default_backend(),
        "asserted": {"min_speedup": min_speedup,
                     "device_like": device_like,
                     "max_cold_regress_pct": 10.0,
                     "max_steady_transfer_bytes": 0,
                     "max_steady_retraces": 0, "full_mode_only_ratio": smoke},
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_residency.json"), "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    assert res_transfers == 0, \
        f"resident steady state shipped {res_transfers} bytes"
    assert res_traces == 0, \
        f"resident steady state compiled {res_traces} kernels"
    if not smoke:
        assert speedup >= min_speedup, \
            f"warm-resident speedup {speedup:.2f}x < {min_speedup}x " \
            f"over cold ({jax.default_backend()} backend)"
        # epsilon absorbs scheduler noise on the paired medians; a real
        # regression from per-row uploads would show far above this
        assert cold_regress_pct < 10.0 or cold_paired_delta_ms < 2.0, \
            f"cold path regressed {cold_regress_pct:.1f}% " \
            f"({cold_paired_delta_ms:.2f}ms paired)"


def _mse_throughput_leg(smoke: bool = False) -> dict:
    """Factory-batched vs serialized leaf dispatch for fingerprint-equal
    MSE traffic (ISSUE 10 acceptance leg). Two measurements:

    1. **Leaf-dispatch closed loop** (`leaf_qps_*` — the acceptance
       number): 8 clients drive the EXACT MSE leaf-stage execution path
       (the `leaf_query_fn` bridge: QueryExecutor over the instance's
       segments with the leaf_agg pushdown context, device engine
       included) with per-query literals; under the pipelined dispatcher
       the concurrent fingerprint-equal leaf stages COALESCE into one
       `jit(vmap)` launch, the serialized arm pays one XLA launch (+
       collective-lock hold on GSPMD hosts) per stage per query. This is
       the layer the tentpole refactors, so its ratio carries the
       structural floor: >= 1.5x on the CPU stand-in, >= 2x on real
       accelerators (each serialized launch additionally pays the ~100ms
       host<->device link there).
    2. **End-to-end MSE join closed loop** (`e2e_*`, context): the same
       leaf shape wrapped in a full broker->stages->mailbox join through
       two MiniClusters with ORDER-ALTERNATING windows + paired
       sequential single-query p50. On the few-core GIL-bound CPU
       stand-in the end-to-end loop is HOST-bound (SQL parse, planning,
       stage submit, mailbox serde dominate at ~9 core-ms/query), so the
       e2e ratio is asserted only on real accelerators; the CPU stand-in
       asserts no e2e regression, paired p50 within noise, and ZERO
       steady-state retraces on the measured windows.

    Both loops warm to a STEADY state first (closed windows repeat until
    throughput stops moving): a cold process's first windows run several
    times slower — thread pools, jit caches, OS scheduling — and would
    poison whichever arm they landed on."""
    import gc
    import shutil
    import statistics as stats
    import tempfile
    import threading

    import jax
    import numpy as np

    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.ops import kernels as _kernels
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    # CPU hosts force the 8-virtual-device mesh CI runs under (same as
    # --batching): every staged kernel is then GSPMD-partitioned, so
    # SERIALIZED leaf dispatch holds the process-global collective lock
    # across launch + sync for every stage of every query — the exact
    # per-launch fixed cost the factory amortizes to once per batch
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:  # older jax: flag path
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    except RuntimeError:
        pass  # backend already initialized (pytest: conftest forced 8)

    num_segments = 4 if smoke else 8
    docs = 2_000
    clients = 8
    window_s = 0.5 if smoke else 2.0
    warm_windows = 1 if smoke else 3
    rounds = 2 if smoke else 4

    fact_schema = Schema.from_dict({
        "schemaName": "bf",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
    dim_schema = Schema.from_dict({
        "schemaName": "bd",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"},
                                {"name": "name", "dataType": "STRING"}]})
    fc = SegmentCreator(TableConfig.from_dict(
        {"tableName": "bf", "tableType": "OFFLINE"}), fact_schema)
    dc = SegmentCreator(TableConfig.from_dict(
        {"tableName": "bd", "tableType": "OFFLINE"}), dim_schema)
    tmp = tempfile.mkdtemp(prefix="bench_mse_tp_")
    seg_dirs = []
    for i in range(num_segments):
        rng = np.random.default_rng(100 + i)
        d = os.path.join(tmp, f"bf_{i}")
        fc.build({"k": rng.integers(0, 8, docs).astype(np.int64),
                  "v": rng.integers(0, 1000, docs).astype(np.int64)},
                 d, f"bf_{i}")
        seg_dirs.append(d)
    dim_dir = os.path.join(tmp, "bd_0")
    dc.build({"k": np.arange(8, dtype=np.int64),
              "name": [f"g{i}" for i in range(8)]}, dim_dir, "bd_0")

    def make_cluster(mode):
        overrides = {"pinot.server.dispatch.mode": mode}
        if mode == "pipelined":
            # the adaptive window (this PR's satellite) sizes the
            # coalesce wait from observed arrivals — the serving shape
            overrides["pinot.server.dispatch.batch.window.ms"] = "auto"
        c = MiniCluster(num_servers=1, use_tpu=True,
                        config=PinotConfiguration(overrides=overrides))
        c.start()
        c.add_table("bf")
        c.add_table("bd")
        for d in seg_dirs:
            c.add_segment("bf", load_segment(d), server_idx=0)
        c.add_segment("bd", load_segment(dim_dir), server_idx=0)
        return c

    # fingerprint-equal MSE joins: the aggregate subquery's literal
    # varies per query (no cache tier can absorb the leaf) while the
    # plan shape is constant, so concurrent leaf stages coalesce on the
    # factory key. The leaf is the scan-heavy global aggregate (the
    # shape whose per-launch fixed cost dominates — exactly what the
    # factory amortizes); the residual join + sort stay tiny.
    def sql_for(j):
        a = (j * 13) % 400
        return ("SELECT d.name, t.s FROM "
                f"(SELECT SUM(f.v) AS s, COUNT(*) AS c FROM bf f "
                f"WHERE f.v BETWEEN {a} AND {a + 500}) t "
                "JOIN bd d ON d.k < t.c ORDER BY d.name LIMIT 20")

    def closed_window(cluster, seq0):
        counts = [0] * clients
        errors = []
        stop_at = time.perf_counter() + window_s

        def client(ci):
            j = seq0 + ci * 1009
            while time.perf_counter() < stop_at:
                resp = cluster.query(sql_for(j))
                if resp.exceptions:
                    errors.append(resp.exceptions)
                    return
                counts[ci] += 1
                j += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # surfaced AFTER join: an assert inside a worker thread dies
        # silently, and a failing arm would otherwise just under-count
        # and corrupt the measured ratio
        assert not errors, errors[0]
        return sum(counts) / (time.perf_counter() - t0)

    def single_p50(cluster, seq0, iters):
        lat = []
        for j in range(iters):
            t0 = time.perf_counter()
            resp = cluster.query(sql_for(seq0 + j))
            assert not resp.exceptions, resp.exceptions
            lat.append((time.perf_counter() - t0) * 1e3)
        return stats.median(lat)

    serial = make_cluster("serialized")
    pipe = make_cluster("pipelined")

    # -- sub-leg 1: the leaf-dispatch layer ----------------------------
    # the exact context _leaf_agg_pushdown builds for this subquery, run
    # through the exact bridge MSE workers use (QueryExecutor + shared
    # engine) — the MSE leaf path minus broker/mailbox, i.e. the layer
    # the factory refactors
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.query.expressions import Function, Identifier, Literal

    leaf_segs = [load_segment(d) for d in seg_dirs]

    def leaf_ctx(j):
        a = (j * 13) % 400
        v = Identifier("v")
        sel = [Function("sum", (v,)),
               Function("count", (Identifier("*"),))]
        q = QueryContext(
            table="bf", select=sel, aliases=[None] * 2, distinct=False,
            filter=Function("between", (v, Literal(a), Literal(a + 500))),
            group_by=[], having=None, order_by=[], limit=1 << 31,
            offset=0, options={"numGroupsLimit": str(1 << 31)})
        q._extract_aggregations()
        return q

    def leaf_loop(engine, seq0):
        counts = [0] * clients
        errors = []
        stop_at = time.perf_counter() + window_s

        def client(ci):
            j = seq0 + ci * 1009
            try:
                while time.perf_counter() < stop_at:
                    ex = QueryExecutor(leaf_segs, use_tpu=True,
                                       engine=engine)
                    results, _stats = ex.execute_context(leaf_ctx(j))
                    assert results
                    counts[ci] += 1
                    j += 1
            except BaseException as e:  # noqa: BLE001 — surface at join
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[0]  # a dead arm must fail the run
        return sum(counts) / (time.perf_counter() - t0)

    def steady_warm(run_window, max_w=3 if smoke else 10):
        """Repeat untimed windows until throughput stops moving (<10%
        window-over-window) — the box takes several seconds of load to
        reach its steady state."""
        prev = run_window(0)
        for w in range(1, max_w):
            cur = run_window(w)
            if abs(cur - prev) <= 0.10 * prev:
                return
            prev = cur

    leaf_eng = {
        "serialized": serial.servers[0].executor._shared_engine(),
        "pipelined": pipe.servers[0].executor._shared_engine(),
    }
    gc.disable()
    try:
        for eng in leaf_eng.values():  # compile + stage once
            QueryExecutor(leaf_segs, use_tpu=True,
                          engine=eng).execute_context(leaf_ctx(0))
        steady_warm(lambda w: leaf_loop(leaf_eng["serialized"],
                                        3000 + w * 61))
        steady_warm(lambda w: leaf_loop(leaf_eng["pipelined"],
                                        3000 + w * 61))
        leaf_ratios, leaf_s_all, leaf_p_all = [], [], []
        leaf_retrace0 = _kernels.trace_count()
        for r in range(rounds):
            order = ["serialized", "pipelined"] if r % 2 == 0 \
                else ["pipelined", "serialized"]
            qps = {}
            for m in order:
                qps[m] = leaf_loop(leaf_eng[m], 4000 + r * 37)
            leaf_ratios.append(qps["pipelined"] / qps["serialized"])
            leaf_s_all.append(qps["serialized"])
            leaf_p_all.append(qps["pipelined"])
        leaf_retraces = _kernels.trace_count() - leaf_retrace0

        # -- sub-leg 2: end-to-end MSE join through the clusters -------
        for c in (serial, pipe):
            for j in range(3):
                resp = c.query(sql_for(j))
                assert not resp.exceptions, resp.exceptions
        steady_warm(lambda w: closed_window(serial, 5000 + w * 61))
        steady_warm(lambda w: closed_window(pipe, 5000 + w * 61))

        ratios, qps_s_all, qps_p_all, p50_deltas = [], [], [], []
        retrace0 = _kernels.trace_count()
        for r in range(rounds):
            if r % 2 == 0:
                qps_s = closed_window(serial, 10_000 + r * 37)
                qps_p = closed_window(pipe, 10_000 + r * 37)
            else:
                qps_p = closed_window(pipe, 10_000 + r * 37)
                qps_s = closed_window(serial, 10_000 + r * 37)
            ratios.append(qps_p / qps_s)
            qps_s_all.append(qps_s)
            qps_p_all.append(qps_p)
            iters = 4 if smoke else 10
            p50_s = single_p50(serial, 20_000 + r * 53, iters)
            p50_p = single_p50(pipe, 20_000 + r * 53, iters)
            p50_deltas.append(p50_p - p50_s)
        retraces = _kernels.trace_count() - retrace0
    finally:
        gc.enable()
        serial.stop()
        pipe.stop()
        shutil.rmtree(tmp, ignore_errors=True)

    platform = jax.devices()[0].platform
    leaf_speedup = stats.median(leaf_ratios)
    e2e_speedup = stats.median(ratios)
    min_leaf = 2.0 if platform != "cpu" else 1.5
    leg = {
        "clients": clients,
        "window_s": window_s,
        "rounds": rounds,
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "platform": platform,
        "leaf_qps_serialized": round(stats.median(leaf_s_all), 1),
        "leaf_qps_factory_batched": round(stats.median(leaf_p_all), 1),
        "leaf_speedup": round(leaf_speedup, 2),
        "leaf_round_ratios": [round(x, 2) for x in leaf_ratios],
        "leaf_retraces_steady": leaf_retraces,
        "e2e_qps_serialized": round(stats.median(qps_s_all), 1),
        "e2e_qps_factory_batched": round(stats.median(qps_p_all), 1),
        "e2e_speedup": round(e2e_speedup, 2),
        "e2e_round_ratios": [round(x, 2) for x in ratios],
        "e2e_p50_single_paired_delta_ms": round(
            stats.median(p50_deltas), 3),
        "e2e_retraces_steady": retraces,
        "asserted": {
            "min_leaf_qps_speedup": min_leaf,
            "min_e2e_qps_speedup": (2.0 if platform != "cpu"
                                    else "report-only (host-bound "
                                         "stand-in; no-regression "
                                         "asserted)"),
            "max_steady_retraces": 0,
            "qps_bar_note": ("leaf layer: 2.0 on accelerators, 1.5 "
                             "structural floor on the CPU stand-in; "
                             "e2e gated on accelerators only — the "
                             "GIL-bound stand-in is host-bound at ~9 "
                             "core-ms/query (see docstring)"),
            "full_mode_only": smoke},
    }
    if not smoke:
        assert leaf_speedup >= min_leaf, \
            f"factory-batched MSE leaf dispatch {leaf_speedup:.2f}x < " \
            f"{min_leaf}x over serialized"
        if platform != "cpu":
            assert e2e_speedup >= 2.0, \
                f"end-to-end MSE join speedup {e2e_speedup:.2f}x < 2x"
        else:
            assert e2e_speedup >= 0.9, \
                f"end-to-end MSE join REGRESSED {e2e_speedup:.2f}x"
        assert leaf_retraces == 0 and retraces == 0, \
            f"steady-state retraces on the MSE leaf path " \
            f"(leaf={leaf_retraces}, e2e={retraces})"
    return leg


def mse_main(smoke: bool = False, out_path: str = None):
    """--mse [--smoke]: MSE reliability + stage-cache A/B (ISSUE 7).

    Chaos-off join/window workload through a real MiniCluster (TCP
    mailboxes, real segments), measuring:

    1. **Deadline-plumbing overhead** — PAIRED adjacent on/off runs of
       an UNCACHED join (per-iteration literals defeat every cache
       tier), overhead = median of per-pair deltas. Pairing + in-pair
       order alternation + untimed gc.collect() between samples cancel
       the dominant noise (GC pauses and thread scheduling on few-core
       hosts; ~10 stage threads race 2 cores here). Asserts <2% p50
       with a small absolute epsilon.
    2. **Leaf-stage cache speedup** — an aggregate-subquery join over
       immutable segments: the leaf stage is a two-phase leaf_agg whose
       per-segment aggregation dominates the query while its per-group
       output block is tiny, so a warm hit on the (version set,
       stage-plan fingerprint) key removes nearly the whole leaf cost.
       Cold clears the stage caches each iteration. Asserts >=1.5x
       warm-over-cold in full mode.
    3. **Factory-batched leaf throughput** (ISSUE 10, `throughput` key)
       — 8-client closed loop of fingerprint-equal MSE joins, pipelined
       (leaf stages coalesce through the unified kernel factory) vs
       serialized leaf dispatch, order-alternating windows with
       median-of-paired-ratios + paired single-query p50 + a zero
       steady-state retrace guard; see _mse_throughput_leg.

    Writes BENCH_mse.json. --smoke shrinks data + iterations and skips
    the ratio asserts (timings are noise at smoke scale)."""
    import gc
    import statistics as stats
    import tempfile

    import numpy as np

    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    num_segments = 8 if smoke else 24
    docs = 4_000 if smoke else 32_000
    iters = 10 if smoke else 24

    fact_schema = Schema.from_dict({
        "schemaName": "bf",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"},
                                {"name": "tag", "dataType": "STRING"}],
        "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
    dim_schema = Schema.from_dict({
        "schemaName": "bd",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"},
                                {"name": "name", "dataType": "STRING"}]})
    fc = SegmentCreator(TableConfig.from_dict(
        {"tableName": "bf", "tableType": "OFFLINE"}), fact_schema)
    dc = SegmentCreator(TableConfig.from_dict(
        {"tableName": "bd", "tableType": "OFFLINE"}), dim_schema)

    tmp = tempfile.mkdtemp(prefix="bench_mse_")
    # one server: the stage pipeline is identical (real mailboxes, all
    # five stages), but the whole fact scan lands on one worker — the
    # cache A/B measures scan-vs-cache, not thread scheduling on a
    # few-core host, and the paired overhead estimator runs quieter
    cluster = MiniCluster(num_servers=1)
    cluster.start()
    cluster.add_table("bf")
    cluster.add_table("bd")
    for i in range(num_segments):
        rng = np.random.default_rng(i)
        d = os.path.join(tmp, f"bf_{i}")
        fc.build({"k": rng.integers(0, 64, docs).astype(np.int64),
                  "tag": [f"t{v}" for v in rng.integers(0, 9, docs)],
                  "v": rng.integers(0, 1000, docs).astype(np.int64)},
                 d, f"bf_{i}")
        cluster.add_segment("bf", load_segment(d), server_idx=0)
    d = os.path.join(tmp, "bd_0")
    dc.build({"k": np.arange(64, dtype=np.int64),
              "name": [f"g{i % 8}" for i in range(64)]}, d, "bd_0")
    cluster.add_segment("bd", load_segment(d), server_idx=0)

    # leaf-scan-heavy join: the string filter makes the fact scan (tag
    # materialization + predicate over every row) the dominant cost
    # while the selective output keeps shuffle/join/agg small — the
    # shape the leaf-stage cache is built for
    join_q = ("SELECT d.name, SUM(f.v) AS s FROM bf f "
              "JOIN bd d ON f.k = d.k "
              "WHERE f.tag = 't3' AND f.v BETWEEN {lo} AND {hi} "
              "GROUP BY d.name ORDER BY d.name LIMIT 100")
    # the cache A/B workload: aggregate-subquery join — the leaf stage
    # is a two-phase leaf_agg (the heavy per-segment aggregation runs ON
    # the scanning worker), its output is 64 per-group intermediates, so
    # the stage cache removes nearly the whole leaf cost on a warm hit
    cache_q = ("SELECT d.name, t.s FROM "
               "(SELECT f.k AS k, SUM(f.v) AS s FROM bf f "
               "WHERE f.tag = 't3' GROUP BY f.k) t "
               "JOIN bd d ON t.k = d.k ORDER BY d.name, t.s LIMIT 200")
    window_q = ("SELECT f.k, f.v, RANK() OVER (PARTITION BY f.k "
                "ORDER BY f.v DESC) AS r FROM bf f "
                "WHERE f.tag = 't1' AND f.v < {lo} "
                "ORDER BY f.k, r LIMIT 50")
    caches = [s.mse_worker.stage_cache for s in cluster.servers]

    def run(sql):
        # GC outside the timed window: object-column serde allocates
        # heavily and a gen-2 pause mid-query (~25ms here) would alias
        # into whichever arm it lands on
        gc.collect()
        t0 = time.perf_counter()
        resp = cluster.query(sql)
        assert not resp.exceptions, resp.exceptions
        return (time.perf_counter() - t0) * 1e3

    def uncached(i):
        return join_q.format(lo=i, hi=i + 30)

    gc.disable()
    try:
        # -- 1. deadline-plumbing overhead: paired on/off ---------------
        # per-iteration literal => fresh fingerprint => every tier
        # (stage cache included) misses: the honest uncached join p50.
        # Adjacent pairs with alternating in-pair order; the estimator
        # is the MEDIAN PER-PAIR DELTA, which cancels ambient drift a
        # pooled median cannot
        for i in range(2):
            run(uncached(900 + i))
        # A/A control: identical arms, same pairing discipline — the
        # measured noise floor the A/B verdict is judged against
        aa = []
        for i in range(max(6, iters // 2)):
            a = run(uncached(700 + 2 * i))
            b = run(uncached(701 + 2 * i))
            aa.append(a - b if i % 2 == 0 else b - a)
        aa_delta_ms = stats.median(aa)
        on_lat, off_lat, deltas = [], [], []
        for i in range(iters):
            first_on = i % 2 == 0
            pair = {}
            for arm in (first_on, not first_on):
                cluster.mse.enforce_deadlines = arm
                pair[arm] = run(uncached(2 * i + (0 if arm else 1)))
            on_lat.append(pair[True])
            off_lat.append(pair[False])
            deltas.append(pair[True] - pair[False])
        p50_off = stats.median(off_lat)
        p50_on = stats.median(on_lat)
        paired_delta_ms = stats.median(deltas)
        overhead_pct = paired_delta_ms / p50_off * 100.0

        # -- 2. leaf-stage cache: cold vs warm --------------------------
        cold_lat, warm_lat = [], []
        run(cache_q)  # warm code paths once
        for _ in range(iters):
            for c in caches:
                c.clear()
            cold_lat.append(run(cache_q))
            run(cache_q)  # populate-confirm pass
            warm_lat.append(run(cache_q))
        p50_cold = stats.median(cold_lat)
        p50_warm = stats.median(warm_lat)
        speedup = p50_cold / p50_warm if p50_warm else 0.0
        hits = sum(c.stats.hits for c in caches)
        assert hits >= iters, f"stage cache never hit ({hits})"

        # -- 3. window workload p50 (context, chaos off) ----------------
        for i in range(2):
            run(window_q.format(lo=200 + i))
        win_lat = [run(window_q.format(lo=300 + i)) for i in range(iters)]
    finally:
        gc.enable()
        cluster.stop()

    # -- 4. factory-batched vs serialized leaf dispatch (ISSUE 10) ------
    throughput = _mse_throughput_leg(smoke=smoke)

    out = {
        "metric": "mse_deadline_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%",
        "p50_join_deadline_off_ms": round(p50_off, 3),
        "p50_join_deadline_on_ms": round(p50_on, 3),
        "paired_delta_ms": round(paired_delta_ms, 3),
        "aa_noise_floor_ms": round(aa_delta_ms, 3),
        "p50_join_cold_ms": round(p50_cold, 3),
        "p50_join_warm_ms": round(p50_warm, 3),
        "stage_cache_speedup": round(speedup, 2),
        "stage_cache_hits": hits,
        "p50_window_ms": round(stats.median(win_lat), 3),
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "smoke": smoke,
        "throughput": throughput,
        "asserted": {"max_overhead_pct": 2.0, "min_cache_speedup": 1.5,
                     "full_mode_only": smoke},
    }
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_mse.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    if not smoke:
        # epsilon absorbs residual scheduler noise (2-core host, ~10
        # stage threads per query); the plumbing itself is time compares
        # at op boundaries, far below either bound
        assert overhead_pct < 2.0 or paired_delta_ms < 2.0, \
            f"deadline plumbing costs {overhead_pct:.2f}% join p50 (>2%)"
        assert speedup >= 1.5, \
            f"leaf-stage cache speedup {speedup:.2f}x < 1.5x warm/cold"


def _groups_build_cluster(tmp: str, num_segments: int, docs: int):
    """4 servers in 2 replica groups (group 0 = servers 0/1, group 1 =
    servers 2/3), every segment fully copied in both groups — the
    fault-domain acceptance topology."""
    import numpy as np

    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    schema = Schema.from_dict({
        "schemaName": "rg",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
    creator = SegmentCreator(TableConfig.from_dict(
        {"tableName": "rg", "tableType": "OFFLINE"}), schema)
    cluster = MiniCluster(num_servers=4)
    cluster.start()
    cluster.add_table("rg", num_replica_groups=2, tenant="bench")
    total = 0
    for i in range(num_segments):
        rng = np.random.default_rng(100 + i)
        d = os.path.join(tmp, f"rg_{i}")
        creator.build({"k": rng.integers(0, 64, docs).astype(np.int64),
                       "v": rng.integers(0, 1000, docs).astype(np.int64)},
                      d, f"rg_{i}")
        cluster.add_segment("rg", load_segment(d), server_idx=i % 2,
                            replicas=[2 + i % 2])
        total += docs
    return cluster, total


def _groups_chaos_journal(tmp: str, seed: int, n_queries: int):
    """One sequential chaos run against the `broker.group.scatter` site:
    a seeded coin kills scatters to group 0 (SIGKILL-equivalent: the
    request raises before the wire) until the failure detector demotes
    the group. Returns (per-query outcomes, per-site decision journal) —
    two same-seed runs must match EXACTLY."""
    from pinot_tpu.utils.failpoints import FaultSchedule

    sched = FaultSchedule([
        ("broker.group.scatter",
         {"error": ConnectionError("chaos: replica group 0 killed"),
          "probability": 0.5, "seed": seed, "where": {"group": 0}})])
    cluster, _total = None, None
    try:
        import shutil
        run_dir = os.path.join(tmp, f"journal_{seed}")
        os.makedirs(run_dir, exist_ok=True)
        cluster, _total = _groups_build_cluster(run_dir, num_segments=4,
                                                docs=500)
        # pin demotion: once the chaos kills one member, group 0 stays
        # out of routing for the whole run — replay must not depend on
        # when a wall-clock backoff happens to expire
        for b in cluster.brokers:
            b.failure_detector.base_backoff_s = 3600.0
            b.failure_detector.max_backoff_s = 3600.0
        sched.arm()
        outcomes = []
        for i in range(n_queries):
            resp = cluster.query(
                f"SELECT COUNT(*), SUM(v) FROM rg WHERE v >= {i % 7}")
            outcomes.append((len(resp.exceptions),
                             resp.rows[0][0] if resp.rows else None))
        decisions = sched.decisions()
        shutil.rmtree(run_dir, ignore_errors=True)
        return outcomes, decisions
    finally:
        sched.disarm()
        if cluster is not None:
            cluster.stop()


def groups_main(smoke: bool = False, out_path: str = None):
    """--groups [--smoke]: replica-group fault-domain acceptance (ISSUE
    8). 2 replica groups x 2 servers, 8-client closed loop:

    1. **all-alive phase** — baseline aggregate QPS.
    2. **group-kill phase** — every member of replica group 0 is killed
       (SIGKILL-equivalent transport death) while the loop runs; the
       loop keeps going. Asserts **zero failed queries** across the
       whole run (the mid-scatter failures fail over: the whole group
       demotes, unanswered segments re-scatter onto group 1) and
       reports the convergent one-group QPS + p99.
    3. **seeded chaos journal** — a sequential run with a seeded coin
       killing `broker.group.scatter` hits on group 0 is executed
       TWICE; outcomes + failpoint decision journals must be identical
       (the per-seed replay contract), digest recorded.

    Writes BENCH_groups.json. --smoke shrinks data + durations and
    skips the throughput-ratio assert (timings are noise at smoke
    scale); zero-failures and replay-identical are asserted always."""
    import hashlib
    import tempfile
    import threading

    num_segments = 4 if smoke else 12
    docs = 800 if smoke else 20_000
    duration_s = 1.2 if smoke else 5.0
    clients = 8

    tmp = tempfile.mkdtemp(prefix="bench_groups_")
    cluster, total_rows = _groups_build_cluster(tmp, num_segments, docs)

    lock = threading.Lock()

    def closed_loop(duration: float):
        """8-client closed loop; returns (latencies_s, failures)."""
        stop_at = time.perf_counter() + duration
        lat, failures = [], []

        def client(cid: int):
            i = cid
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                resp = cluster.query(
                    f"SELECT COUNT(*), SUM(v) FROM rg WHERE v >= {i % 7}")
                dt = time.perf_counter() - t0
                with lock:
                    lat.append(dt)
                    if resp.exceptions:
                        failures.append(resp.exceptions)
                i += clients
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return lat, failures

    def p(q, vals):
        if not vals:
            return 0.0
        return sorted(vals)[min(len(vals) - 1,
                                max(0, round(q * len(vals)) - 1))]

    # warm code paths (parse/plan/serde jit noise off the measurement)
    for i in range(4):
        resp = cluster.query(f"SELECT COUNT(*), SUM(v) FROM rg "
                             f"WHERE v >= {i}")
        assert not resp.exceptions, resp.exceptions

    lat_all, fail_all = closed_loop(duration_s)
    qps_all = len(lat_all) / duration_s

    # -- the kill: every member of group 0, while the loop runs --------
    killer = threading.Timer(duration_s * 0.25,
                             cluster.kill_replica_group, args=("rg", 0))
    killer.start()
    lat_kill, fail_kill = closed_loop(duration_s)
    killer.join()
    qps_kill = len(lat_kill) / duration_s

    # -- steady state on the surviving group ---------------------------
    lat_one, fail_one = closed_loop(duration_s)
    qps_one = len(lat_one) / duration_s
    cluster.stop()

    # -- seeded chaos journal: replay must be byte-identical -----------
    seed = 20260803
    run_a = _groups_chaos_journal(tmp, seed, n_queries=12 if smoke else 40)
    run_b = _groups_chaos_journal(tmp, seed, n_queries=12 if smoke else 40)
    replay_identical = run_a == run_b
    journal_digest = hashlib.sha1(repr(run_a).encode()).hexdigest()[:16]
    chaos_failed = sum(1 for exc_count, _rows in run_a[0] if exc_count)

    failed = len(fail_all) + len(fail_kill) + len(fail_one)
    out = {
        "metric": "group_kill_failed_queries",
        "value": failed,
        "unit": "queries",
        "qps_all_alive": round(qps_all, 1),
        "qps_during_kill": round(qps_kill, 1),
        "qps_one_group": round(qps_one, 1),
        "p50_all_alive_ms": round(p(0.50, lat_all) * 1e3, 2),
        "p99_all_alive_ms": round(p(0.99, lat_all) * 1e3, 2),
        "p99_during_kill_ms": round(p(0.99, lat_kill) * 1e3, 2),
        "p99_one_group_ms": round(p(0.99, lat_one) * 1e3, 2),
        "queries_total": len(lat_all) + len(lat_kill) + len(lat_one),
        "chaos_journal_digest": journal_digest,
        "chaos_replay_identical": replay_identical,
        "chaos_run_failed_queries": chaos_failed,
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "total_rows": total_rows,
        "clients": clients,
        "smoke": smoke,
        "asserted": {"failed_queries": 0, "replay_identical": True,
                     "chaos_failed_queries": 0,
                     "min_one_group_qps_frac": None if smoke else 0.25},
    }
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_groups.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    assert failed == 0, \
        f"{failed} queries failed across the group-kill run: " \
        f"{(fail_all + fail_kill + fail_one)[:3]}"
    assert chaos_failed == 0, \
        f"{chaos_failed} chaos-journal queries failed: {run_a[0][:5]}"
    assert replay_identical, "same-seed chaos journal diverged"
    if not smoke:
        assert qps_one >= 0.25 * qps_all, \
            f"one-group throughput collapsed: {qps_one:.0f} vs " \
            f"{qps_all:.0f} all-alive QPS"


def batching_main(smoke: bool = False, out_path: str = None):
    """--batching [--smoke]: A/B the unified kernel factory (ISSUE 9).

    Two closed-loop legs, each run twice IN THE SAME PROCESS against
    `pinot.server.dispatch.mode=serialized` (the pre-ring inline
    dispatch baseline):

      mixed_table — three tables with the same plan shape but their own
        data, segment counts, and doc counts (padding into one shape
        bucket); 8 clients spread across them. The PR-4 coalescer could
        never batch these (keys included the concrete segment batch);
        the unified factory stacks their column blocks along a leading
        batch axis and launches once per bucket.
      doc_sharded — a (segments x docs) mesh engine, which PR 4
        excluded from batching entirely (`vmap` over `shard_map`
        unsupported). The factory vmaps INSIDE shard_map, so the whole
        batch pays one set of collectives — and on CPU hosts holds the
        process-global collective lock once per BATCH, not per query.

    Records, per leg: closed-loop aggregate QPS (median of per-round
    paired ratios), paired single-query p50, batch stats, steady-state
    retrace count, and the DEVICE-level amortization (single-launch vs
    batch-8 per-query launch+sync). Two bars, residency-bench style
    (backend-gated — see PR 6's warm-vs-cold precedent):

      * device_speedup_batch8 >= 2x on BOTH legs, always — the layer
        the kernel factory refactors. On real accelerators the
        per-launch fixed cost includes the ~100ms host<->device link,
        so this amortization IS the serving win.
      * closed-loop QPS >= 2x on real accelerators; >= 1.5x structural
        floor on the few-core CPU stand-in, where each query's
        GIL-serialized host work (result assembly, futures) is
        comparable to its device time and is NOT deleted by batching —
        that host share caps the end-to-end ratio regardless of how
        well launches amortize (observed 1.7-2.3x across host
        throttling states; a sub-floor run usually means the box
        changed state mid-window — rerun).

    Also asserts zero steady-state retraces and no single-query p50
    regression beyond noise, and that cross-table stacked batches
    actually carried the mixed leg. Writes BENCH_batching.json.
    --smoke shrinks data + durations to fit the tier-1 timeout.

    On CPU hosts the mixed leg forces the 8-virtual-device mesh CI runs
    under — every kernel is GSPMD-partitioned, so serialized mode holds
    the collective lock across dispatch + fetch per query, the exact
    regime the factory amortizes."""
    import contextlib
    import statistics as stats
    import tempfile
    import threading

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax: the XLA flag still takes effect when the backend is
        # not yet initialized (no-op under pytest, where conftest already
        # forced 8 virtual devices)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    except RuntimeError:
        pass  # backend already initialized (in-process smoke run)
    if len(jax.devices()) < 8:
        raise SystemExit("batching bench needs 8 (virtual) devices")

    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.ops import dispatch as dispatch_mod
    from pinot_tpu.ops import kernels
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.parallel.mesh import make_mesh
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    clients = 8
    duration_s = 1.2 if smoke else 12.0
    p50_iters = 12 if smoke else 40
    rounds = 2 if smoke else 6
    # three tables, one plan shape: same columns, own doc counts that
    # pad into ONE 2048-doc bucket, segment counts that pad into one
    # S bucket — the mixed dashboard fleet
    table_docs = {"ssb_a": (4, 1500), "ssb_b": (4, 1800), "ssb_c": (3, 2000)}

    tmp = tempfile.mkdtemp(prefix="bench_batching_")
    dates = np.array([y * 10000 + m * 100 + d
                      for y in range(1992, 1999)
                      for m in range(1, 13) for d in range(1, 29)],
                     dtype=np.int32)

    def build_table(name, num_segments, docs, seed):
        schema = Schema(name, [
            FieldSpec("lo_orderdate", DataType.INT, FieldType.DIMENSION),
            FieldSpec("lo_discount", DataType.INT, FieldType.DIMENSION),
            FieldSpec("lo_quantity", DataType.INT, FieldType.DIMENSION),
            FieldSpec("lo_extendedprice", DataType.INT, FieldType.METRIC),
        ])
        tc = TableConfig(name, TableType.OFFLINE)
        tc.indexing.no_dictionary_columns = ["lo_extendedprice"]
        tc.indexing.compression = "PASS_THROUGH"
        creator = SegmentCreator(tc, schema)
        segs = []
        for i in range(num_segments):
            rng = np.random.default_rng(seed + i)
            out = os.path.join(tmp, f"{name}_{i}")
            creator.build({
                "lo_orderdate": dates[rng.integers(0, len(dates), docs)],
                "lo_discount": rng.integers(0, 11, docs).astype(np.int32),
                "lo_quantity": rng.integers(1, 51, docs).astype(np.int32),
                "lo_extendedprice": rng.integers(
                    90_000, 10_000_000, docs).astype(np.int32),
            }, out, f"{name}_{i}")
            segs.append(load_segment(out))
        return segs

    tables = {name: build_table(name, n, docs, 7000 + 100 * i)
              for i, (name, (n, docs)) in enumerate(table_docs.items())}
    names = list(tables)

    def sql_for(table, a):
        return ("SELECT SUM(lo_extendedprice * lo_discount), COUNT(*) "
                f"FROM {table} "
                "WHERE lo_orderdate BETWEEN 19940101 AND 19940131 "
                f"AND lo_discount BETWEEN {a} AND {a + 2} "
                "AND lo_quantity BETWEEN 26 AND 35")

    def warm_buckets(launches):
        """Trace every batched (plan, bucket, variant) shape the
        measured window can produce — broadcast per bucket, stacked per
        bucket when >1 table — so steady-state retraces are a real
        regression signal, not warmup noise."""
        lead = launches[0]
        guard = dispatch_mod._CPU_COLLECTIVE_LOCK if lead.collective \
            else contextlib.nullcontext()
        b = 2
        n_uniq = len({ln.cols_key for ln in launches})
        while b <= max(2, dispatch_mod._pow2(clients)):
            variants = [False] + ([True] if len(launches) > 1 else [])
            for stacked in variants:
                kern = lead.factory(b, stacked)
                if stacked:
                    members = [launches[i % len(launches)]
                               for i in range(b)]
                    with guard:
                        jax.block_until_ready(kern(
                            tuple(m.cols for m in members),
                            tuple(m.params for m in members),
                            tuple(m.num_docs for m in members),
                            D=lead.D, G=lead.G))
                else:
                    with guard:
                        jax.block_until_ready(kern(
                            lead.cols, (lead.params,) * b, lead.num_docs,
                            D=lead.D, G=lead.G))
            # same-cols member-grouped (dedup) variants: a stacked batch
            # with duplicate tables dedups its stack, keyed (plan, B, U)
            # — warm every U bucket a b-member batch over these tables
            # can produce so the measured window compiles nothing
            if lead.dedup_factory is not None and len(launches) > 1:
                u = 1
                while u <= dispatch_mod._pow2(min(b, n_uniq)):
                    kern = lead.dedup_factory(b, u)
                    uniqs = [launches[i % len(launches)]
                             for i in range(u)]
                    idx = np.zeros(b, np.int32)
                    with guard:
                        jax.block_until_ready(kern(
                            tuple(m.cols for m in uniqs),
                            (lead.params,) * b,
                            tuple(m.num_docs for m in uniqs),
                            idx, D=lead.D, G=lead.G))
                    u *= 2
            b *= 2

    def closed_window(jobs, window_s):
        """jobs: per-client (executor, ctxs) pairs."""
        counts = [0] * len(jobs)
        stop_at = time.perf_counter() + window_s

        def client(ci):
            ex, ctxs = jobs[ci]
            j = 0
            while time.perf_counter() < stop_at:
                ex.execute_context(ctxs[j % len(ctxs)])
                counts[ci] += 1
                j += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(jobs))]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts), time.perf_counter() - t0

    def run_leg(make_engine, leg_tables, warm_stacked, leg):
        """One serialized-vs-unified A/B over alternating closed-loop
        windows; returns the leg report dict. `leg` labels the engines'
        dispatcher metrics so each leg reads ITS OWN batch stats — the
        registry is process-global and cumulative, so unlabelled reads
        would report the other leg's maxima."""
        labels = {"bench_leg": leg}

        def make_mode(mode):
            engine = make_engine(mode, labels)
            exs = {tn: QueryExecutor(segs, use_tpu=True, engine=engine)
                   for tn, segs in leg_tables.items()}
            jobs = []
            for ci in range(clients):
                tn = list(leg_tables)[ci % len(leg_tables)]
                ctxs = [QueryContext.from_sql(sql_for(tn, a))
                        for a in range(8)]
                jobs.append((exs[tn], ctxs))
            for ex, ctxs in jobs:   # stage + compile the single path
                for c in ctxs:
                    results, _stats = ex.execute_context(c)
                    assert results, "bench query must stage on-device"
            return engine, jobs

        eng_ser, jobs_ser = make_mode("serialized")
        eng_uni, jobs_uni = make_mode("pipelined")
        launches = []
        if warm_stacked:
            for tn, segs in leg_tables.items():
                prep = eng_uni._prepare_agg(
                    segs, QueryContext.from_sql(sql_for(tn, 0)))
                assert prep is not None
                launches.append(prep[3])
            assert len({ln.batch_key for ln in launches}) == 1, \
                "tables must share one shape bucket for this bench"
        else:
            prep = eng_uni._prepare_agg(
                next(iter(leg_tables.values())),
                QueryContext.from_sql(sql_for(next(iter(leg_tables)), 0)))
            assert prep is not None
            launches.append(prep[3])
        warm_buckets(launches)

        # DEVICE-level amortization: steady-state launch+sync time of one
        # single-query kernel vs one batch-8 launch (stacked when the leg
        # mixes tables), per query. This is the layer the kernel factory
        # refactors, and the number that transfers to real accelerators —
        # there the per-launch fixed cost includes the ~100ms host<->
        # device link, so amortizing launches IS the serving win. The
        # closed-loop QPS ratio below additionally carries per-query
        # HOST work (result assembly, futures — GIL-serialized on the
        # few-core CPU stand-in) that batching does not delete, which
        # caps it well under the device-level ratio on fast hosts.
        lead = launches[0]
        guard = dispatch_mod._CPU_COLLECTIVE_LOCK if lead.collective \
            else contextlib.nullcontext()
        B = 8

        def timed(fn, iters=20):
            with guard:
                jax.block_until_ready(fn())  # warm
                t0 = time.perf_counter()
                for _ in range(iters):
                    jax.block_until_ready(fn())
                return (time.perf_counter() - t0) / iters * 1e3

        single_ms = timed(lead.call)
        kern = lead.factory(B, warm_stacked)
        if warm_stacked:
            members = [launches[i % len(launches)] for i in range(B)]
            clist = tuple(m.cols for m in members)
            plist8 = tuple(m.params for m in members)
            ndlist = tuple(m.num_docs for m in members)
            batch8_ms = timed(lambda: kern(clist, plist8, ndlist,
                                           D=lead.D, G=lead.G))
        else:
            plist8 = (lead.params,) * B
            batch8_ms = timed(lambda: kern(lead.cols, plist8,
                                           lead.num_docs,
                                           D=lead.D, G=lead.G))
        device_speedup = single_ms / (batch8_ms / B)

        # paired single-client p50: strictly interleaved A/B samples
        def one(jobs, i):
            ex, ctxs = jobs[i % len(jobs)]
            t0 = time.perf_counter()
            ex.execute_context(ctxs[i % len(ctxs)])
            return (time.perf_counter() - t0) * 1e3

        for i in range(4):
            one(jobs_ser, i), one(jobs_uni, i)
        lat_ser, lat_uni = [], []
        for i in range(p50_iters):
            if i % 2 == 0:
                lat_ser.append(one(jobs_ser, i))
                lat_uni.append(one(jobs_uni, i))
            else:
                lat_uni.append(one(jobs_uni, i))
                lat_ser.append(one(jobs_ser, i))

        reg = eng_uni._dispatcher._metrics
        batch_t0 = reg.timer("dispatch_batch_size", labels=labels)
        batch_c0, batch_max0 = batch_t0.count, batch_t0.max_ms
        xtab0 = reg.meter("dispatch_batch_cross_table", labels=labels)
        traces0 = kernels.trace_count()
        ser_n = ser_wall = uni_n = uni_wall = 0.0
        round_ratios = []
        for _r in range(rounds):
            # alternate which mode goes first within the round: a fixed
            # order hands the second window a systematically different
            # box (frequency scaling, neighbors) on a small shared host
            order = [(jobs_ser, "s"), (jobs_uni, "u")] if _r % 2 == 0 \
                else [(jobs_uni, "u"), (jobs_ser, "s")]
            qps = {}
            for jobs, tag in order:
                n, w = closed_window(jobs, duration_s / rounds)
                qps[tag] = n / w
                if tag == "s":
                    ser_n += n
                    ser_wall += w
                else:
                    uni_n += n
                    uni_wall += w
            round_ratios.append(qps["u"] / max(qps["s"], 1e-9))
        batch_t = reg.timer("dispatch_batch_size", labels=labels)
        paired_delta_ms = stats.median(
            p - s for s, p in zip(lat_ser, lat_uni))
        serialized = {
            "qps": round(ser_n / ser_wall, 2),
            "queries_completed": int(ser_n),
            "p50_single_ms": round(stats.median(lat_ser), 2),
        }
        unified = {
            "qps": round(uni_n / uni_wall, 2),
            "queries_completed": int(uni_n),
            "p50_single_ms": round(stats.median(lat_uni), 2),
            "retraces_steady": kernels.trace_count() - traces0,
            "batch_launches": batch_t.count - batch_c0,
            "batch_size_max": max(batch_t.max_ms, batch_max0),
            "cross_table_batched_queries": int(
                reg.meter("dispatch_batch_cross_table",
                          labels=labels) - xtab0),
        }
        # PAIRED per-round ratio, median across rounds: each round's two
        # windows run back to back, so the per-round ratio cancels the
        # multi-second throughput drift this shared box exhibits (a slow
        # patch landing on one mode's only long window would otherwise
        # masquerade as a pipeline property); totals are also reported
        return {
            "serialized": serialized,
            "unified": unified,
            "speedup": round(stats.median(round_ratios), 2),
            "speedup_total": round(
                (uni_n / uni_wall) / max(ser_n / ser_wall, 1e-9), 2),
            "round_ratios": [round(r, 2) for r in round_ratios],
            "device_single_ms": round(single_ms, 3),
            "device_batch8_per_query_ms": round(batch8_ms / B, 3),
            "device_speedup_batch8": round(device_speedup, 2),
            "p50_paired_delta_ms": round(paired_delta_ms, 3),
            "p50_single_delta_pct": round(
                paired_delta_ms / serialized["p50_single_ms"] * 100.0, 2),
        }

    # the serving-default 2ms coalesce window stays: a wider window on
    # the few-core CPU stand-in turns each batch into a lock-step
    # barrier (every client's GIL-bound host phase synchronizes behind
    # the launch instead of overlapping the next batch's device time) —
    # partial bucket-padded batches amortize launches while keeping the
    # host and device phases pipelined
    def overrides(mode):
        return {"pinot.server.dispatch.mode": mode}

    # leg 1: mixed tables on the default (GSPMD segments-mesh) engine
    mixed = run_leg(
        lambda mode, labels: TpuOperatorExecutor(
            config=PinotConfiguration(overrides=overrides(mode)),
            metrics_labels=labels),
        tables, warm_stacked=True, leg="mixed")

    # leg 2: doc-sharded mesh engine (4 segments x 2 docs), one table —
    # the path that previously fell off batching entirely
    mesh = make_mesh(jax.devices()[:8], doc_axis=2)
    sharded = run_leg(
        lambda mode, labels: TpuOperatorExecutor(
            mesh=mesh, config=PinotConfiguration(
                overrides=overrides(mode)),
            metrics_labels=labels),
        {"ssb_a": tables["ssb_a"]}, warm_stacked=False, leg="doc_sharded")

    on_accelerator = jax.devices()[0].platform != "cpu"
    qps_floor = 2.0 if on_accelerator else 1.5
    out = {
        "metric": "unified_factory_batching_qps_speedup",
        "value": round(min(mixed["speedup"], sharded["speedup"]), 2),
        "unit": "x",
        "clients": clients,
        "duration_s": duration_s,
        "tables": {tn: {"segments": n, "docs": d}
                   for tn, (n, d) in table_docs.items()},
        "smoke": smoke,
        "platform": jax.devices()[0].platform,
        "mixed_table": mixed,
        "doc_sharded": sharded,
        "asserted": {"min_device_speedup_batch8": 2.0,
                     "min_qps_speedup": qps_floor,
                     "qps_bar_note": "2.0 on accelerators; 1.5 structural "
                                     "floor on the GIL-bound CPU stand-in "
                                     "(see docstring)",
                     "max_p50_regress_pct": 5.0,
                     "max_steady_retraces": 0},
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_batching.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    for leg_name, leg in (("mixed_table", mixed), ("doc_sharded", sharded)):
        assert leg["unified"]["retraces_steady"] == 0, \
            f"{leg_name} steady-state retraces: " \
            f"{leg['unified']['retraces_steady']}"
    assert mixed["unified"]["cross_table_batched_queries"] > 0, \
        "no cross-table batch formed in the measured window"
    if not smoke:
        for leg_name, leg in (("mixed_table", mixed),
                              ("doc_sharded", sharded)):
            assert leg["device_speedup_batch8"] >= 2.0, \
                f"{leg_name} device amortization " \
                f"{leg['device_speedup_batch8']:.2f}x < 2x"
            assert leg["speedup"] >= qps_floor, \
                f"{leg_name} speedup {leg['speedup']:.2f}x < {qps_floor}x"
            # epsilon absorbs scheduler noise on few-ms medians
            assert leg["p50_single_delta_pct"] < 5.0 \
                or leg["p50_paired_delta_ms"] < 0.5, \
                f"{leg_name} single-client p50 regressed " \
                f"{leg['p50_single_delta_pct']:.1f}%"


# ---------------------------------------------------------------------------
# --startree: device star-tree pre-agg vs scan (ISSUE 16)
# ---------------------------------------------------------------------------

def startree_main(smoke: bool = False, out_path: str = None):
    """--startree [--smoke]: A/B the device star-tree pre-agg leg
    (ISSUE 16) against the device scan path.

    Scaling leg — the same dimensional distribution is built at a base
    row count and at ``factor``x rows (100x in the full run), each with
    a star-tree. Two engines run every query: one serving from the
    pre-agg leg, one with ``pinot.server.startree.enabled=false`` (the
    scan path). Both end-to-end p50 and the DEVICE-level steady-state
    launch+sync time are recorded. The star-tree table's pre-agg record
    count is bounded by the dimension-combination space, not the row
    count, so its kernel reads the SAME [S, D] shape at both sizes —
    device time stays ~flat while the scan kernel's D bucket grows with
    the data. (End-to-end p50 carries fixed per-query host work — parse,
    plan, result assembly — so the device-level ratio is the asserted
    signal; the p50s are reported for color.)

    Coalesce leg — 8 clients loop fingerprint-equal star-tree queries
    (same plan, different predicate constants) against one pipelined
    engine: the unified-factory coalesce key (plan fingerprint + shape
    bucket) must batch them (`dispatch_batch_size` max > 1) with ZERO
    steady-state retraces after the shape buckets are warmed.

    Every query is parity-checked against the scan engine (1e-6
    relative, the repo's device-parity standard — the pre-agg leg runs
    f32 like the scan path). Writes BENCH_startree.json. --smoke
    shrinks rows/iters/windows to fit the tier-1 timeout."""
    import contextlib
    import statistics as stats
    import tempfile
    import threading

    import jax

    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  StarTreeIndexConfig, TableConfig,
                                  TableType)
    from pinot_tpu.ops import dispatch as dispatch_mod
    from pinot_tpu.ops import kernels
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    base_docs = 1_200 if smoke else 3_000
    factor = 10 if smoke else 100
    num_segments = 2 if smoke else 4
    p50_iters = 6 if smoke else 30
    dev_iters = 8 if smoke else 25
    window_s = 0.8 if smoke else 2.5
    clients = 8

    tmp = tempfile.mkdtemp(prefix="bench_startree_")
    schema = Schema("stb", [
        FieldSpec("country", DataType.STRING),
        FieldSpec("browser", DataType.STRING),
        FieldSpec("locale", DataType.STRING),
        FieldSpec("impressions", DataType.LONG, FieldType.METRIC),
        FieldSpec("cost", DataType.DOUBLE, FieldType.METRIC),
    ])
    tc = TableConfig("stb", TableType.OFFLINE)
    tc.indexing.star_tree_configs = [StarTreeIndexConfig(
        dimensions_split_order=["country", "browser", "locale"],
        function_column_pairs=["SUM__impressions", "MAX__cost",
                               "SUM__cost"],
        max_leaf_records=10)]
    creator = SegmentCreator(tc, schema)

    def build(tag, docs_per_seg, seed):
        segs = []
        for i in range(num_segments):
            rng = np.random.default_rng(seed + i)
            out = os.path.join(tmp, f"stb_{tag}_{i}")
            creator.build({
                "country": [f"c{v}" for v in
                            rng.integers(0, 20, docs_per_seg)],
                "browser": [f"b{v}" for v in
                            rng.integers(0, 6, docs_per_seg)],
                "locale": [f"l{v}" for v in
                           rng.integers(0, 10, docs_per_seg)],
                "impressions": rng.integers(
                    0, 1000, docs_per_seg).astype(np.int64),
                "cost": rng.random(docs_per_seg) * 100,
            }, out, f"stb_{tag}_{i}")
            segs.append(load_segment(out))
        return segs

    sizes = {"1x": build("1x", base_docs // num_segments, 4000),
             f"{factor}x": build("nx", base_docs * factor // num_segments,
                                 5000)}

    def parity_sqls(alt):
        return [
            "SELECT SUM(impressions), COUNT(*) FROM stb "
            f"WHERE country = 'c{alt}'",
            "SELECT SUM(impressions) FROM stb "
            f"WHERE country IN ('c1','c2','c{alt}') AND browser = 'b2'",
            "SELECT MAX(cost), SUM(cost), COUNT(*) FROM stb",
            "SELECT browser, SUM(impressions), COUNT(*) FROM stb "
            f"WHERE locale = 'l{alt % 10}' "
            "GROUP BY browser ORDER BY browser LIMIT 100",
        ]

    p50_sql = parity_sqls(3)[0]

    def rows_close(a, b):
        if len(a) != len(b):
            return False
        for x, y in zip(a, b):
            if isinstance(x, float) or isinstance(y, float):
                if not (abs(float(x) - float(y))
                        <= 1e-6 * max(1.0, abs(float(x)))):
                    return False
            elif x != y:
                return False
        return True

    labels = {"bench_leg": "startree"}
    eng_tree = TpuOperatorExecutor(
        config=PinotConfiguration(), metrics_labels=labels)
    eng_scan = TpuOperatorExecutor(
        config=PinotConfiguration(overrides={
            "pinot.server.startree.enabled": False}),
        metrics_labels={"bench_leg": "startree_scan"})
    reg = eng_tree._dispatcher._metrics

    from pinot_tpu.query.context import QueryContext

    def timed_device(launch, iters):
        guard = dispatch_mod._CPU_COLLECTIVE_LOCK if launch.collective \
            else contextlib.nullcontext()
        with guard:
            jax.block_until_ready(launch.call())  # warm
            t0 = time.perf_counter()
            for _ in range(iters):
                jax.block_until_ready(launch.call())
            return (time.perf_counter() - t0) / iters * 1e3

    report_sizes = {}
    for tag, segs in sizes.items():
        ex_tree = QueryExecutor(segs, use_tpu=True, engine=eng_tree)
        ex_scan = QueryExecutor(segs, use_tpu=True, engine=eng_scan)
        served0 = reg.meter("startree_served", labels=labels)
        for sql in parity_sqls(3) + parity_sqls(7):
            rt = ex_tree.execute(sql)
            rs = ex_scan.execute(sql)
            assert not rt.exceptions and not rs.exceptions, (tag, sql)
            ra = sorted(map(str, rt.result_table.rows))
            rb = sorted(map(str, rs.result_table.rows))
            assert len(ra) == len(rb), (tag, sql)
            for a, b in zip(ra, rb):
                assert rows_close(eval(a), eval(b)), (tag, sql, a, b)
        served = reg.meter("startree_served", labels=labels) - served0
        assert served > 0, f"{tag}: no query served from the pre-agg leg"

        # device-level steady state: one launch+sync, params cache warm
        ctx = QueryContext.from_sql(p50_sql)
        prep_t = eng_tree._prepare_startree(segs, ctx)
        assert prep_t is not None, f"{tag}: pre-agg leg refused to stage"
        launch_t = prep_t[4]
        prep_s = eng_scan._prepare_agg(segs, QueryContext.from_sql(p50_sql))
        assert prep_s is not None
        launch_s = prep_s[3]
        dev_tree_ms = timed_device(launch_t, dev_iters)
        dev_scan_ms = timed_device(launch_s, dev_iters)

        def p50(ex):
            lat = []
            for _ in range(p50_iters):
                t0 = time.perf_counter()
                ex.execute(p50_sql)
                lat.append((time.perf_counter() - t0) * 1e3)
            return stats.median(lat)

        report_sizes[tag] = {
            "docs": sum(s.num_docs for s in segs),
            "preagg_records": sum(
                int(f.tree.meta.num_records) for f in prep_t[2]),
            "device_tree_ms": round(dev_tree_ms, 3),
            "device_scan_ms": round(dev_scan_ms, 3),
            "p50_tree_ms": round(p50(ex_tree), 2),
            "p50_scan_ms": round(p50(ex_scan), 2),
            "startree_served": int(served),
        }

    big = f"{factor}x"
    tree_growth = report_sizes[big]["device_tree_ms"] \
        / max(report_sizes["1x"]["device_tree_ms"], 1e-9)
    scan_growth = report_sizes[big]["device_scan_ms"] \
        / max(report_sizes["1x"]["device_scan_ms"], 1e-9)

    # -- coalesce leg: fingerprint-equal queries share one launch -----
    segs = sizes[big]
    ex_tree = QueryExecutor(segs, use_tpu=True, engine=eng_tree)
    coal_sqls = [parity_sqls(i)[0] for i in range(clients)]
    for sql in coal_sqls:  # stage + params-cache every predicate
        ex_tree.execute(sql)
    launch = eng_tree._prepare_startree(
        segs, QueryContext.from_sql(coal_sqls[0]))[4]
    guard = dispatch_mod._CPU_COLLECTIVE_LOCK if launch.collective \
        else contextlib.nullcontext()
    b = 2
    while b <= dispatch_mod._pow2(clients):
        kern = launch.factory(b, False)
        with guard:
            jax.block_until_ready(kern(
                launch.cols, (launch.params,) * b, launch.num_docs,
                D=launch.D, G=launch.G))
        b *= 2
    traces0 = kernels.trace_count()
    batch_t0 = reg.timer("dispatch_batch_size", labels=labels)
    count0, max0 = batch_t0.count, batch_t0.max_ms

    stop_at = time.perf_counter() + window_s
    done = [0] * clients

    def client(ci):
        j = 0
        while time.perf_counter() < stop_at:
            ex_tree.execute(coal_sqls[(ci + j) % clients])
            done[ci] += 1
            j += 1

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    retraces = kernels.trace_count() - traces0
    batch_t = reg.timer("dispatch_batch_size", labels=labels)
    coalesce = {
        "clients": clients,
        "queries_completed": int(sum(done)),
        "qps": round(sum(done) / wall, 2),
        "batch_launches": batch_t.count - count0,
        "batch_size_max": max(batch_t.max_ms, max0),
        "retraces_steady": retraces,
    }

    out = {
        "metric": "startree_device_time_growth_at_{}".format(big),
        "value": round(tree_growth, 2),
        "unit": "x",
        "scan_growth": round(scan_growth, 2),
        "smoke": smoke,
        "platform": jax.devices()[0].platform,
        "sizes": report_sizes,
        "coalesce": coalesce,
        "asserted": {
            "parity": "pre-agg rows == scan rows, 1e-6 relative",
            "max_steady_retraces": 0,
            "min_batch_size": 2,
            "full_run_only": "device tree growth ~flat (< 3x) while "
                             "rows grow {}x; scan growth exceeds "
                             "tree growth".format(factor),
        },
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_startree.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    assert coalesce["retraces_steady"] == 0, \
        f"steady-state retraces: {coalesce['retraces_steady']}"
    assert coalesce["batch_size_max"] >= 2, \
        "fingerprint-equal star-tree queries never coalesced"
    if not smoke:
        assert tree_growth < 3.0, \
            f"pre-agg device time grew {tree_growth:.2f}x at {big} rows"
        assert scan_growth > tree_growth, \
            f"scan growth {scan_growth:.2f}x did not exceed tree " \
            f"growth {tree_growth:.2f}x"
        assert report_sizes[big]["device_tree_ms"] \
            < report_sizes[big]["device_scan_ms"], \
            "pre-agg kernel slower than the scan kernel at scale"


# ---------------------------------------------------------------------------
# --ingest: production ingestion under mixed read/write load (ISSUE 11)
# ---------------------------------------------------------------------------

def _pct(q, vals):
    if not vals:
        return 0.0
    return sorted(vals)[min(len(vals) - 1, max(0, round(q * len(vals)) - 1))]


def ingest_main(smoke: bool = False, out_path: str = None):
    """--ingest [--smoke]: the production-ingestion acceptance driver.

    One upsert REALTIME table consumed from an in-memory stream while a
    closed-loop query fleet reads it — the reference's "millions of
    events per second ingested while serving queries" scenario (SURVEY
    §6) at bench scale. Four legs:

      * mixed load — N producer threads + 8 query clients + a freshness
        prober (publish a sentinel pk, poll until queryable). Reports
        sustained events/sec, freshness p50/p95 (event ts -> queryable),
        query p50/p99, and the ZERO-GAP assertion: query p99 inside
        seal windows (mutable rotation -> commit) vs steady windows —
        the async build pipeline means a seal is never query-visible
        (bounded by CPU contention on the stand-in, gated tighter on
        accelerators).
      * backpressure — an overdriven producer against a small
        `pinot.server.ingest.memory.bytes` budget: mutable+pending
        bytes stay BOUNDED (adaptive fetch -> pause -> seal -> resume)
        while the same load with no budget grows unbounded; every row
        still lands.
      * chaos — a seeded SimulatedCrash (ingest.upsert.apply) kills the
        consumer MID-BATCH under the query load; queries keep serving
        from the old segment set with zero failures while a new manager
        recovers from the committed offsets + validDocIds snapshots;
        convergence is exactly-once (no duplicate, no lost rows).
      * journal — the chaos leg runs twice with the same seed; the
        failpoint decision journals must be byte-identical (the PR-3
        chaos bar).

    Writes BENCH_ingest.json (backend-gated like BENCH_residency.json).
    """
    import threading

    import jax

    from pinot_tpu.ingest.memory_stream import InMemoryStream
    from pinot_tpu.ingest.realtime_manager import (
        IngestionDelayTracker, RealtimeSegmentDataManager)
    from pinot_tpu.ingest.stream import LongMsgOffset, StreamConfig
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType, UpsertConfig)
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.server.data_manager import TableDataManager
    from pinot_tpu.utils.config import PinotConfiguration
    from pinot_tpu.utils.failpoints import SimulatedCrash, failpoints
    from pinot_tpu.utils.metrics import MetricsRegistry
    import tempfile

    on_cpu = jax.devices()[0].platform == "cpu"
    if smoke:
        window_s, clients, n_pks, flush_rows = 2.0, 3, 400, 500
        max_events, probe_every = 5_000, 0.05
        bp_budget, bp_events, bp_flush = 64 * 1024, 4_000, 400
        chaos_events, chaos_pks = 3_000, 300
    else:
        window_s, clients, n_pks, flush_rows = 20.0, 8, 20_000, 15_000
        max_events, probe_every = 120_000, 0.025
        bp_budget, bp_events, bp_flush = 512 * 1024, 100_000, 5_000
        chaos_events, chaos_pks = 24_000, 2_000

    schema = Schema("u", [
        FieldSpec("pk", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("ver", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("val", DataType.INT, FieldType.METRIC),
    ], primary_key_columns=["pk"])

    def table_cfg():
        tc = TableConfig("u", TableType.REALTIME)
        tc.upsert = UpsertConfig(mode="FULL", comparison_column="ver")
        return tc

    SQLS = [
        "SELECT COUNT(*), SUM(val) FROM u LIMIT 5",
        "SELECT d, COUNT(*), SUM(val) FROM u GROUP BY d ORDER BY d LIMIT 30",
        "SELECT pk, val FROM u WHERE val > 500 ORDER BY val DESC LIMIT 10",
    ]

    engine = TpuOperatorExecutor(config=PinotConfiguration())
    metrics = MetricsRegistry("bench_ingest")

    def run_query(serving, sql):
        tdm = serving["tdm"]
        sdms = tdm.acquire_segments()
        try:
            ex = QueryExecutor([s.segment for s in sdms], use_tpu=True,
                               engine=engine)
            return ex.execute(sql)
        finally:
            TableDataManager.release_all(sdms)

    def query_fleet(serving, stop_evt, n_clients):
        lats, fails = [], []
        lock = threading.Lock()

        def client(ci):
            i = ci
            while not stop_evt.is_set():
                sql = SQLS[i % len(SQLS)]
                i += 1
                t0 = time.time()
                try:
                    r = run_query(serving, sql)
                    if r.exceptions:
                        raise RuntimeError(str(r.exceptions[:1]))
                    with lock:
                        lats.append((t0, time.time() - t0))
                except Exception as e:  # noqa: BLE001
                    with lock:
                        fails.append(repr(e))
        ts = [threading.Thread(target=client, args=(ci,))
              for ci in range(n_clients)]
        for t in ts:
            t.start()
        return ts, lats, fails

    # ------------------------------------------------------------------
    # leg 1: mixed read/write load + freshness + seal windows
    # ------------------------------------------------------------------
    topic = InMemoryStream("bench_ingest_mixed", 1)
    store = tempfile.mkdtemp(prefix="bench_ingest_")
    tdm = TableDataManager("u_REALTIME")
    commits, opens = [], []
    tracker = IngestionDelayTracker(metrics=metrics)
    mgr = RealtimeSegmentDataManager(
        table_cfg(), schema, StreamConfig(
            stream_type="inmemory", topic="bench_ingest_mixed",
            flush_threshold_rows=flush_rows),
        0, tdm, store, metrics=metrics, ingestion_delay_tracker=tracker,
        on_commit=lambda n, o: commits.append((time.time(), n, o)),
        on_open=lambda n: opens.append((time.time(), n)))

    last_val = {}
    published = [0]
    pub_lock = threading.Lock()  # producer + prober both publish
    stop_evt = threading.Event()
    rng = np.random.default_rng(7)

    def producer():
        ver = 0
        while not stop_evt.is_set() and published[0] < max_events:
            if published[0] - mgr.rows_indexed > 5_000:
                # bounded-lag producer: a producer running unboundedly
                # ahead of a GIL-bound consumer only measures queue
                # growth; the sustained number is consumption-bound
                # either way (the backpressure leg measures the
                # overdriven case explicitly)
                time.sleep(0.002)
                continue
            now_ms = int(time.time() * 1000)
            for _ in range(200):
                if published[0] >= max_events:
                    break
                pk = int(rng.integers(0, n_pks))
                val = int(rng.integers(0, 1000))
                ver += 1
                with pub_lock:
                    topic.publish({"pk": pk, "ver": ver, "d": pk % 20,
                                   "val": val}, ts_ms=now_ms)
                    last_val[pk] = val
                    published[0] += 1

    freshness = []

    def prober():
        i = 0
        while not stop_evt.is_set():
            i += 1
            pk = 10**12 + i
            t0 = time.time()
            with pub_lock:
                topic.publish({"pk": pk, "ver": 1, "d": 0, "val": 0},
                              ts_ms=int(t0 * 1000))
                last_val[pk] = 0
                published[0] += 1
            sql = f"SELECT COUNT(*) FROM u WHERE pk = {pk} LIMIT 5"
            while not stop_evt.is_set():
                r = run_query({"tdm": tdm}, sql)
                if not r.exceptions and r.rows and r.rows[0][0] >= 1:
                    freshness.append(time.time() - t0)
                    break
                time.sleep(0.002)
            time.sleep(probe_every)

    mgr.start()
    prod_t = threading.Thread(target=producer)
    probe_t = threading.Thread(target=prober)
    t_start = time.time()
    prod_t.start()
    probe_t.start()
    fleet, lats, fails = query_fleet({"tdm": tdm}, stop_evt, clients)
    time.sleep(window_s)
    prod_stop = time.time()
    # let consumption fully drain before the final exactness check
    deadline = time.time() + 180
    while time.time() < deadline and mgr.rows_indexed < published[0]:
        time.sleep(0.02)
    stop_evt.set()
    for t in [prod_t, probe_t, *fleet]:
        t.join(timeout=10)
    drained = mgr.rows_indexed
    elapsed = prod_stop - t_start
    mgr.stop(drain=True)
    events_per_sec = drained / max(time.time() - t_start, 1e-9)

    # exactly-once visibility after the drain: one row per pk, last wins
    final = run_query({"tdm": tdm}, "SELECT COUNT(*), SUM(val) FROM u "
                                    "LIMIT 5").rows[0]
    expect_count, expect_sum = len(last_val), float(sum(last_val.values()))

    # seal windows: [rotation, commit] pairs (first open = initial ctor)
    seal_windows = []
    rot = [t for t, _n in opens[1:]]
    com = [t for t, _n, _o in commits]
    for i in range(min(len(rot), len(com))):
        seal_windows.append((rot[i], com[i] + 0.05))
    in_seal, steady = [], []
    for t0, dt in lats:
        if any(a <= t0 <= b for a, b in seal_windows):
            in_seal.append(dt)
        else:
            steady.append(dt)
    InMemoryStream.delete("bench_ingest_mixed")

    # ------------------------------------------------------------------
    # leg 2: backpressure — bounded bytes vs unbounded growth
    # ------------------------------------------------------------------
    def backpressure_leg(budget):
        name = f"bench_ingest_bp_{budget}"
        t2 = InMemoryStream(name, 1)
        tdm2 = TableDataManager("u_REALTIME")
        cfg = PinotConfiguration(overrides={
            "pinot.server.ingest.memory.bytes": budget,
            "pinot.server.ingest.fetch.max.rows": 2000,
        })
        m2 = RealtimeSegmentDataManager(
            table_cfg(), schema, StreamConfig(
                stream_type="inmemory", topic=name,
                flush_threshold_rows=bp_flush),
            0, tdm2, tempfile.mkdtemp(prefix="bench_ingest_bp_"),
            config=cfg, metrics=metrics)
        for i in range(bp_events):  # overdriven: everything is queued
            t2.publish({"pk": i, "ver": 1, "d": i % 20, "val": 1})
        peak = [0]
        done = threading.Event()

        def sampler():
            while not done.is_set():
                peak[0] = max(peak[0], m2.ingest_bytes())
                time.sleep(0.005)
        st = threading.Thread(target=sampler)
        m2.start()
        st.start()
        deadline = time.time() + 120
        while time.time() < deadline and m2.rows_indexed < bp_events:
            time.sleep(0.02)
        rows = m2.rows_indexed
        done.set()
        st.join()
        m2.stop(drain=True)
        InMemoryStream.delete(name)
        return peak[0], rows

    bounded_peak, bounded_rows = backpressure_leg(bp_budget)
    unbounded_peak, _rows = backpressure_leg(0)

    # ------------------------------------------------------------------
    # leg 3: chaos — seeded consumer SIGKILL mid-batch + journal replay
    # ------------------------------------------------------------------
    def chaos_leg(seed, tag):
        name = f"bench_ingest_chaos_{tag}"
        t3 = InMemoryStream(name, 1)
        store3 = tempfile.mkdtemp(prefix=f"bench_ingest_chaos_{tag}_")
        tdm3 = TableDataManager("u_REALTIME")
        commits3 = []
        rng3 = np.random.default_rng(seed)
        last3 = {}
        ver = 0
        for _ in range(chaos_events):  # deterministic pre-published log
            pk = int(rng3.integers(0, chaos_pks))
            val = int(rng3.integers(0, 1000))
            ver += 1
            t3.publish({"pk": pk, "ver": ver, "d": pk % 20, "val": val})
            last3[pk] = val
        fp = failpoints.arm("ingest.upsert.apply",
                            error=SimulatedCrash("kill"), times=1,
                            probability=0.002, seed=seed)
        sc = StreamConfig(stream_type="inmemory", topic=name,
                          flush_threshold_rows=max(200, chaos_events // 8))
        m3 = RealtimeSegmentDataManager(
            table_cfg(), schema, sc, 0, tdm3, store3, metrics=metrics,
            on_commit=lambda n, o: commits3.append((n, o)))
        serving = {"tdm": tdm3}
        stop3 = threading.Event()
        fleet3, lats3, fails3 = query_fleet(serving, stop3, clients)
        m3.start()
        deadline = time.time() + 60
        while time.time() < deadline and not m3._crashed:
            time.sleep(0.01)
        crashed = m3._crashed
        m3.stop()  # joins the dead thread; flushes in-flight builds

        # restart exactly as a fresh server process would
        resume = max((int(str(o)) for _n, o in commits3), default=0)
        tdm4 = TableDataManager("u_REALTIME")
        recovered = []
        for nm in sorted(os.listdir(store3)):
            path = os.path.join(store3, nm)
            if os.path.isdir(path) and not nm.startswith("_"):
                seg = load_segment(path)
                tdm4.add_segment(seg)
                recovered.append(seg)
        m4 = RealtimeSegmentDataManager(
            table_cfg(), schema, sc, 0, tdm4, store3, metrics=metrics,
            start_offset=LongMsgOffset(resume), start_seq=len(recovered),
            recover_segments=recovered)
        m4.start()
        serving["tdm"] = tdm4  # queries swap to the recovered view

        want = (len(last3), float(sum(last3.values())))
        got = (None, None)
        deadline = time.time() + 120
        while time.time() < deadline:
            r = run_query(serving, "SELECT COUNT(*), SUM(val) FROM u "
                                   "LIMIT 5")
            if not r.exceptions:
                got = (r.rows[0][0], float(r.rows[0][1]))
                if got == want:
                    break
            time.sleep(0.05)
        stop3.set()
        for t in fleet3:
            t.join(timeout=10)
        m4.stop(drain=True)
        decisions = list(fp.decisions)
        failpoints.disarm("ingest.upsert.apply")
        InMemoryStream.delete(name)
        return {"crashed": crashed, "converged": got == want,
                "got": got, "want": want, "failed_queries": len(fails3),
                "queries": len(lats3), "decisions": decisions}

    seed = 20260803
    chaos_a = chaos_leg(seed, "a")
    chaos_b = chaos_leg(seed, "b")
    replay_identical = chaos_a["decisions"] == chaos_b["decisions"]

    seal_p99 = _pct(0.99, in_seal)
    steady_p99 = _pct(0.99, steady)
    seal_gate = 2.0 if not on_cpu else 6.0
    out = {
        "metric": "ingest_events_per_sec_sustained",
        "value": round(events_per_sec),
        "unit": "events/s",
        "events_published": published[0],
        "events_indexed": drained,
        "window_s": round(elapsed, 1),
        "clients": clients,
        "freshness_p50_ms": round(_pct(0.50, freshness) * 1e3, 1),
        "freshness_p95_ms": round(_pct(0.95, freshness) * 1e3, 1),
        "query_p50_ms": round(_pct(0.50, [d for _t, d in lats]) * 1e3, 2),
        "query_p99_ms": round(_pct(0.99, [d for _t, d in lats]) * 1e3, 2),
        "queries_total": len(lats),
        "failed_queries": len(fails),
        "seals": len(commits),
        "seal_window_p99_ms": round(seal_p99 * 1e3, 2),
        "steady_window_p99_ms": round(steady_p99 * 1e3, 2),
        "seal_window_queries": len(in_seal),
        "exact_count": [final[0], expect_count],
        "exact_sum": [float(final[1]), expect_sum],
        "backpressure": {
            "budget_bytes": bp_budget,
            "bounded_peak_bytes": bounded_peak,
            "unbounded_peak_bytes": unbounded_peak,
            "rows": bounded_rows,
        },
        "chaos": {k: v for k, v in chaos_a.items() if k != "decisions"},
        "chaos_replay_identical": replay_identical,
        "host_cpu_cores": os.cpu_count(),
        "backend": jax.devices()[0].platform,
        "smoke": smoke,
        "asserted": {
            "failed_queries": 0,
            "exactly_once": True,
            "seal_p99_over_steady_max": seal_gate,
            "bounded_peak_over_budget_max": 1.5,
            "replay_identical": True,
        },
    }
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_ingest.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))

    # -- gates ---------------------------------------------------------
    assert len(fails) == 0, f"mixed-load queries failed: {fails[:3]}"
    assert drained == published[0], (drained, published[0])
    assert final[0] == expect_count and float(final[1]) == expect_sum, \
        (final, expect_count, expect_sum)
    assert len(commits) >= 2, "no seals happened — widen the window"
    assert bounded_rows == bp_events, "backpressure starved the consumer"
    assert bounded_peak <= bp_budget * 1.5, \
        f"mutable bytes escaped the budget: {bounded_peak} vs {bp_budget}"
    assert chaos_a["crashed"] and chaos_b["crashed"], "chaos never fired"
    assert chaos_a["failed_queries"] == 0 and chaos_b["failed_queries"] == 0
    assert chaos_a["converged"] and chaos_b["converged"], \
        (chaos_a["got"], chaos_a["want"])
    assert replay_identical, "same-seed chaos journal diverged"
    if not smoke:
        assert unbounded_peak > bounded_peak, \
            "backpressure contrast missing (unbounded never grew)"
        if in_seal and steady:
            assert seal_p99 <= seal_gate * max(steady_p99, 1e-4), \
                f"seal-visible p99 spike: {seal_p99*1e3:.1f}ms vs " \
                f"steady {steady_p99*1e3:.1f}ms"


def health_main(smoke: bool = False, out_path: "str | None" = None):
    """--health [--smoke]: the fleet health plane must be ~free (ISSUE 14).

    Two overhead legs over identical MiniClusters in one process, with
    an A/A noise floor like --trace-overhead:

    * accounting leg — pinot.workload.accounting.enabled=false (no
      ChargeSlips, no WorkloadStats rollup) vs on (the default):
      strictly interleaved paired A/B. Asserts <2% p50.
    * sampling leg — alternating BLOCKS of queries with the metrics
      sampler + SLO watchdog running (aggressive 50ms interval — 20x
      the default cadence) vs stopped, on the accounting-off cluster.
      A background thread can't be isolated per query pair, so blocks
      alternate to cancel drift. Asserts <2% p50.

    Also asserts the qualitative contract: the accounting-on side's
    WorkloadStats carry real rows-scanned totals and a per-tenant cost
    gauge. Writes BENCH_health.json; smoke runs in tier-1 via
    tests/test_health_plane.py.
    """
    import statistics as stats
    import tempfile

    import numpy as np

    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.health.history import MetricsHistory, MetricsSampler
    from pinot_tpu.health.slo import SloWatchdog
    from pinot_tpu.health.workload import get_workload
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    num_segments = 8 if smoke else 32
    docs = 5_000 if smoke else 20_000
    iters = 16 if smoke else 40
    blocks = 4 if smoke else 8
    block_n = 8 if smoke else 16
    query = ("SELECT SUM(v), COUNT(*) FROM t "
             "WHERE k BETWEEN 100 AND 800 OPTION(skipCache=true)")

    schema = Schema("t", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    creator = SegmentCreator(TableConfig("t", TableType.OFFLINE), schema)
    tmp = tempfile.mkdtemp(prefix="bench_health_")
    segments = []
    for i in range(num_segments):
        rng = np.random.default_rng(i)
        d = os.path.join(tmp, f"seg_{i}")
        creator.build({"k": rng.integers(0, 1000, docs).astype(np.int32),
                       "v": rng.integers(0, 100, docs).astype(np.int32)},
                      d, f"t_{i}")
        segments.append(load_segment(d))

    def make_cluster(cfg):
        c = MiniCluster(num_servers=2, config=cfg)
        c.start()
        c.add_table("t")
        for i, seg in enumerate(segments):
            c.add_segment("t", seg, server_idx=i % 2)
        return c

    off_cfg = PinotConfiguration(overrides={
        "pinot.workload.accounting.enabled": False})
    on_cfg = PinotConfiguration()  # defaults: accounting armed
    c_off = make_cluster(off_cfg)
    c_on = make_cluster(on_cfg)

    get_workload("server").clear()

    def one(c, q=query):
        t0 = time.perf_counter()
        resp = c.query(q)
        assert not resp.exceptions, resp.exceptions
        return (time.perf_counter() - t0) * 1e3

    def paired_pct(run_a, run_b, n):
        ratios, deltas, a_lat, b_lat = [], [], [], []
        for i in range(n):
            if i % 2 == 0:
                a, b = run_a(), run_b()
            else:
                b, a = run_b(), run_a()
            a_lat.append(a)
            b_lat.append(b)
            ratios.append(b / a)
            deltas.append(b - a)
        return ((stats.median(ratios) - 1.0) * 100.0,
                stats.median(deltas),
                stats.median(a_lat), stats.median(b_lat))

    #: the sampler under test: aggressive interval, both role
    #: registries' worth of series, SLO targets armed so every tick
    #: pays full burn-rate evaluation
    slo_cfg = PinotConfiguration(overrides={
        "pinot.slo.query.p99.ms": 10_000.0,
        "pinot.slo.error.rate": 0.01,
        "pinot.slo.window.short.seconds": 5.0,
        "pinot.slo.window.long.seconds": 30.0})
    hist = MetricsHistory(1024)
    try:
        for _ in range(8):
            one(c_off), one(c_on)
        noise_pct, _, _, _ = paired_pct(
            lambda: one(c_off),
            lambda: (one(c_on), one(c_off))[1], iters)
        noise_pct = abs(noise_pct)

        # -- leg 1: accounting off vs on, paired --------------------------
        acct_pct, acct_delta_ms, p50_off, p50_acct = paired_pct(
            lambda: one(c_off), lambda: one(c_on), iters)

        # -- leg 2: sampler+watchdog running vs stopped, block-paired -----
        with_s, without_s = [], []
        for b in range(blocks):
            sampler = MetricsSampler("server", interval_s=0.05,
                                     history=hist)
            sampler.add_hook(SloWatchdog("server", hist,
                                         config=slo_cfg).evaluate)
            run_first = b % 2 == 0
            for phase in (0, 1):
                sampling = (phase == 0) == run_first
                if sampling:
                    ticks_before = len(hist)
                    sampler.start()
                lat = [one(c_off) for _ in range(block_n)]
                if sampling:
                    # a fast block can finish inside the sampler's first
                    # 50ms wait; hold it open (latencies are already
                    # collected) until it has ticked so every sampling
                    # block actually exercises the sample+watchdog path
                    deadline = time.perf_counter() + 2.0
                    while (len(hist) == ticks_before
                           and time.perf_counter() < deadline):
                        time.sleep(0.005)
                    sampler.stop()
                    with_s.append(stats.median(lat))
                else:
                    without_s.append(stats.median(lat))
        p50_sampling = stats.median(with_s)
        p50_nosampling = stats.median(without_s)
        sampling_pct = (p50_sampling / p50_nosampling - 1.0) * 100.0

        # qualitative contract: the on-side actually attributed work
        wl = get_workload("server")
        top = wl.top(5)
        assert top and top[0]["rowsScanned"] > 0, top
        assert wl.tenants(), "no per-tenant cost accumulated"
        assert len(hist) > 0, "sampler appended nothing"
    finally:
        c_off.stop()
        c_on.stop()

    out = {
        "metric": "health_plane_overhead_pct",
        "value": round(max(acct_pct, sampling_pct), 3),
        "unit": "%",
        "accounting_overhead_pct": round(acct_pct, 3),
        "accounting_paired_delta_ms": round(acct_delta_ms, 3),
        "sampling_overhead_pct": round(sampling_pct, 3),
        "p50_off_ms": round(p50_off, 3),
        "p50_accounting_ms": round(p50_acct, 3),
        "p50_sampling_ms": round(p50_sampling, 3),
        "p50_nosampling_ms": round(p50_nosampling, 3),
        "aa_noise_floor_pct": round(noise_pct, 3),
        "sampler_interval_ms": 50.0,
        "history_samples": len(hist),
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "iters": iters,
        "smoke": smoke,
        "asserted_max_pct": 2.0,
    }
    if out_path is None and not smoke:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_health.json")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))
    # bounds mirror --trace-overhead: the STRICT <2% bar belongs to the
    # full run (the committed BENCH_health.json); smoke runs inside
    # tier-1 on a loaded CI box whose A/A floor alone can be 3-8%, so it
    # asserts the qualitative contract (no multi-ms / tens-of-percent
    # regression) without flaking on scheduler noise
    if smoke:
        bound = max(25.0, 2.0 * noise_pct + 5.0)
        eps_ms = max(2.0, 0.10 * p50_off)
    else:
        bound = max(2.0, noise_pct + 1.0)
        eps_ms = 0.5
    assert acct_pct < bound or acct_delta_ms < eps_ms, \
        (f"workload accounting costs {acct_pct:.2f}% p50 "
         f"({acct_delta_ms:.3f}ms paired; bound {bound:.2f}%, "
         f"A/A floor {noise_pct:.2f}%)")
    assert sampling_pct < bound \
        or (p50_sampling - p50_nosampling) < eps_ms, \
        (f"metrics sampling costs {sampling_pct:.2f}% p50 "
         f"(bound {bound:.2f}%, A/A floor {noise_pct:.2f}%)")


def overload_main(smoke: bool = False, out_path: "str | None" = None):
    """--overload [--smoke]: admission control must preserve goodput
    under offered load past capacity (ISSUE 15).

    An OPEN-LOOP driver — arrivals on a clock, never waiting for
    responses, the only honest way to measure overload — at 1x/2x/4x of
    measured capacity against two MiniClusters in one process:

    * protected — admission control + bounded scheduler queues + the
      per-table retry budget + overload-aware hedging (the defaults);
    * unprotected — ``pinot.server.admission.enabled=false`` +
      ``pinot.broker.retry.budget.enabled=false`` (the pre-PR-15
      behavior), hedging equally enabled.

    Per-query execution cost is pinned by a fixed-delay
    ``server.execute.before`` failpoint so capacity is deterministic
    (4 worker threads / delay) and an over-admitted query measurably
    BURNS a worker thread — the resource the protection exists to
    guard. Every query ships a fixed end-to-end budget; outcomes are
    counted as ok (clean in-budget answer), typed (errorCode partial/
    rejection), or hung (no typed outcome within budget + grace).

    Asserted (full run): protected goodput at 4x >= 70% of measured 1x
    capacity while the unprotected leg collapses below that bar; ZERO
    hung queries anywhere; protection overhead < 2% p50 at 1x against
    the A/A noise floor. The overhead A/B toggles the protection flags
    on ONE live cluster in alternating blocks (same sockets, same
    threads) — comparing two separate cluster instances measures
    cluster-placement noise, not the protection code. Smoke (tier-1 via
    tests/test_overload.py) asserts the qualitative contract with
    CI-noise-tolerant bounds. Writes BENCH_overload.json.
    """
    import statistics as stats
    import tempfile
    import threading

    import numpy as np

    from pinot_tpu.broker.failure_detector import ConnectionFailureDetector
    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration
    from pinot_tpu.utils.failpoints import failpoints

    num_segments = 4
    docs = 2_000
    # one worker thread per server + a long pinned exec keep the 4x
    # offered load CHEAP on the host (tens of arrivals/s): the A/B must
    # measure the protection dynamics, not the 2-core box's GIL
    exec_delay_s = 0.12 if smoke else 0.2
    budget_ms = 1000.0 if smoke else 1500.0
    duration_s = 1.6 if smoke else 4.0
    hung_grace_s = 2.5
    mults = (1, 4) if smoke else (1, 2, 4)
    overhead_iters = 12 if smoke else 40
    workers_total = 2  # 2 servers x 1 scheduler thread

    schema = Schema("t", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    creator = SegmentCreator(TableConfig("t", TableType.OFFLINE), schema)
    tmp = tempfile.mkdtemp(prefix="bench_overload_")
    segments = []
    for i in range(num_segments):
        rng = np.random.default_rng(i)
        d = os.path.join(tmp, f"seg_{i}")
        creator.build({"k": rng.integers(0, 1000, docs).astype(np.int32),
                       "v": rng.integers(0, 100, docs).astype(np.int32)},
                      d, f"t_{i}")
        segments.append(load_segment(d))

    base = {
        "pinot.server.query.num.threads": 1,
        "pinot.broker.timeout.ms": int(budget_ms),
        "pinot.broker.hedge.enabled": True,
        "pinot.broker.hedge.delay.min.ms": 40,
        "pinot.broker.hedge.delay.max.ms": 300,
    }
    # queue limit sized so a full queue's drain (limit x exec / worker)
    # still fits the budget with the exec itself on top
    prot_cfg = PinotConfiguration(overrides={
        **base, "pinot.server.admission.queue.limit": 3})
    unprot_cfg = PinotConfiguration(overrides={
        **base,
        "pinot.server.admission.enabled": False,
        "pinot.broker.retry.budget.enabled": False,
        "pinot.brownout.enabled": False})

    def make_cluster(cfg):
        c = MiniCluster(num_servers=2, config=cfg)
        c.start()
        c.add_table("t")
        for i, seg in enumerate(segments):
            # full replication: per-query routing lands the whole set on
            # ONE server (round-robin across queries), the twin is the
            # hedge/retry target
            c.add_segment("t", seg, server_idx=0, replicas=[1])
        return c

    c_prot = make_cluster(prot_cfg)
    c_unprot = make_cluster(unprot_cfg)
    query = ("SELECT SUM(v), COUNT(*) FROM t WHERE k BETWEEN 100 AND 800 "
             "OPTION(skipCache=true)")

    def one(c):
        """One clean closed-loop query latency (warmup + overhead legs).
        A lone deadline partial here means the HOST stalled (loaded CI
        box), not that the protection failed — retry a couple of times
        before treating it as real; anything non-250 stays fatal."""
        from pinot_tpu.utils import errorcodes as _ec
        for attempt in range(3):
            t0 = time.perf_counter()
            resp = c.query(query)
            if not resp.exceptions:
                return (time.perf_counter() - t0) * 1e3
            codes = {e.get("errorCode") for e in resp.exceptions}
            assert codes == {_ec.EXECUTION_TIMEOUT}, resp.exceptions
        raise AssertionError(
            f"3 consecutive deadline misses at idle load: "
            f"{resp.exceptions}")

    def set_protection(flag: bool) -> None:
        """Toggle the protection machinery on the LIVE protected
        cluster: the overhead A/B must flip only the code under test,
        never the sockets/threads it runs on."""
        for s in c_prot.servers:
            s.transport.admission.enabled = flag
        for b in c_prot.brokers:
            b._retry_budget.enabled = flag

    def block_pct(toggle: bool, blocks: int, block_n: int):
        """Block-paired p50s on c_prot: alternating protection-on/-off
        blocks (toggle=True) or all-off blocks split the same way
        (toggle=False — the A/A floor). Returns (overhead %, delta ms,
        baseline p50 ms)."""
        on_p50, off_p50 = [], []
        for blk in range(blocks):
            run_on = blk % 2 == 0
            for phase in (0, 1):
                protected = (phase == 0) == run_on
                set_protection(protected if toggle else False)
                lat = [one(c_prot) for _ in range(block_n)]
                (on_p50 if ((phase == 0) == run_on)
                 else off_p50).append(stats.median(lat))
        set_protection(True)
        base_p50 = stats.median(off_p50)
        return ((stats.median(on_p50) / base_p50 - 1.0) * 100.0,
                stats.median(on_p50) - base_p50, base_p50)

    def reset_brokers():
        """Between legs: fresh failure-detector state (an earlier leg's
        exiles must not leak), settled server queues."""
        for c in (c_prot, c_unprot):
            for b in c.brokers:
                b.failure_detector = ConnectionFailureDetector()

    def open_loop(c, rate_qps, leg_duration_s, pool):
        counts = {"ok": 0, "typed": 0, "hung": 0}
        ok_lat = []
        abandoned = set()  # query ids the waiter already counted hung
        lock = threading.Lock()
        budget_s = budget_ms / 1000.0

        def fire_one(qid):
            t0 = time.perf_counter()
            typed = False
            untyped_raise = False
            try:
                resp = c.query(query)
                typed = bool(resp.exceptions)
            except Exception:  # noqa: BLE001 — an untyped raise is a bug
                untyped_raise = True
            dur = time.perf_counter() - t0
            with lock:
                if qid in abandoned:
                    return  # the waiter counted this query hung already
                if untyped_raise or dur > budget_s + hung_grace_s:
                    counts["hung"] += 1
                elif typed:
                    counts["typed"] += 1
                else:
                    counts["ok"] += 1
                    ok_lat.append(dur * 1e3)

        n = max(1, int(rate_qps * leg_duration_s))
        start = time.perf_counter()
        futs = []
        for i in range(n):
            target = start + i / rate_qps
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futs.append(pool.submit(fire_one, i))
        deadline = time.perf_counter() + budget_s + hung_grace_s + 5.0
        for i, f in enumerate(futs):
            remaining = max(0.0, deadline - time.perf_counter())
            try:
                f.result(timeout=remaining)
            except Exception:  # noqa: BLE001 — hung; exactly-once with
                with lock:     # fire_one via the abandoned set
                    abandoned.add(i)
                    counts["hung"] += 1
        elapsed = max(leg_duration_s, time.perf_counter() - start)
        return {
            "offered_qps": round(rate_qps, 2),
            "queries": n,
            "ok": counts["ok"],
            "typed": counts["typed"],
            "hung": counts["hung"],
            "goodput_qps": round(counts["ok"] / elapsed, 2),
            "ok_p50_ms": (round(stats.median(ok_lat), 1)
                          if ok_lat else None),
        }

    from pinot_tpu.utils.metrics import get_registry
    try:
        # -- warm both clusters (EWMA estimates, routing, compile) -----
        for _ in range(6):
            one(c_prot), one(c_unprot)

        # -- overhead leg at 1x, NO injected delay: the protection's
        # own cost is a few dict lookups per query ---------------------
        blocks = 4 if smoke else 8
        noise_pct, _, _ = block_pct(False, blocks, overhead_iters // 2)
        noise_pct = abs(noise_pct)
        over_pct, over_delta_ms, p50_unprot = block_pct(
            True, blocks, overhead_iters // 2)

        # -- pin per-query cost, measure capacity closed-loop ----------
        fp = failpoints.arm("server.execute.before", delay=exec_delay_s)
        cap_pool = ThreadPoolExecutor(max_workers=workers_total + 2)
        cap_t0 = time.perf_counter()
        cap_n = [0]
        cap_stop = cap_t0 + (1.6 if smoke else 3.0)

        def cap_loop():
            while time.perf_counter() < cap_stop:
                resp = c_prot.query(query)
                if not resp.exceptions:
                    # a typed rejection here is the protection working
                    # (momentary rr imbalance overflows one server's
                    # tiny queue); capacity counts CLEAN answers only
                    cap_n[0] += 1
        cap_futs = [cap_pool.submit(cap_loop)
                    for _ in range(workers_total + 2)]
        for f in cap_futs:
            f.result(timeout=60)
        cap_pool.shutdown(wait=True)
        capacity_qps = cap_n[0] / (time.perf_counter() - cap_t0)
        # the structural ceiling: workers / per-query delay
        capacity_qps = min(capacity_qps, workers_total / exec_delay_s)

        # -- open-loop legs --------------------------------------------
        legs = {}
        pool = ThreadPoolExecutor(max_workers=256,
                                  thread_name_prefix="overload-client")
        for mult in mults:
            for name, c in (("protected", c_prot),
                            ("unprotected", c_unprot)):
                reset_brokers()
                legs[f"{name}_{mult}x"] = open_loop(
                    c, mult * capacity_qps, duration_s, pool)
                time.sleep(budget_ms / 1000.0 * 0.5)  # drain queues
        pool.shutdown(wait=True)
        failpoints.clear()

        reg_server = get_registry("server").sample()["counters"]
        admission_rejects = sum(
            v for k, v in reg_server.items()
            if k.startswith("server_admission_rejected"))
        reg_broker = get_registry("broker").sample()["counters"]
        retries_issued = sum(v for k, v in reg_broker.items()
                             if k.startswith("broker_retries_issued"))
        broker_queries = sum(v for k, v in reg_broker.items()
                             if k == "broker_queries"
                             or k.startswith("broker_queries{"))
    finally:
        failpoints.clear()
        c_prot.stop()
        c_unprot.stop()

    prot_4x = legs["protected_4x"]["goodput_qps"]
    unprot_4x = legs["unprotected_4x"]["goodput_qps"]
    hung_total = sum(leg["hung"] for leg in legs.values())
    out = {
        "metric": "overload_protected_goodput_frac_of_capacity_at_4x",
        "value": round(prot_4x / capacity_qps, 3),
        "unit": "fraction",
        "capacity_qps": round(capacity_qps, 2),
        "exec_delay_ms": exec_delay_s * 1e3,
        "budget_ms": budget_ms,
        "legs": legs,
        "protected_4x_goodput_qps": prot_4x,
        "unprotected_4x_goodput_qps": unprot_4x,
        "collapse_ratio": round(prot_4x / max(unprot_4x, 0.01), 2),
        "hung_queries_total": hung_total,
        "admission_rejects": admission_rejects,
        "broker_retries_issued": retries_issued,
        "broker_queries": broker_queries,
        "retry_ratio": round(retries_issued / max(broker_queries, 1), 4),
        "overhead_pct_at_1x": round(over_pct, 3),
        "overhead_paired_delta_ms": round(over_delta_ms, 3),
        "aa_noise_floor_pct": round(noise_pct, 3),
        "p50_unprotected_ms": round(p50_unprot, 3),
        "smoke": smoke,
        "asserted": {"min_protected_frac_at_4x": 0.7 if not smoke else 0.4,
                     "max_overhead_pct": 2.0, "max_hung": 0},
    }
    if out_path is None and not smoke:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_overload.json")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))

    # -- gates ----------------------------------------------------------
    assert hung_total == 0, f"{hung_total} hung/untyped queries"
    if smoke:
        # qualitative bars: a loaded CI box makes absolute goodput
        # noisy, but protection must still clearly hold the line
        assert prot_4x >= 0.4 * capacity_qps, \
            (f"protected goodput {prot_4x} < 40% of capacity "
             f"{capacity_qps:.1f} at 4x (smoke)")
        bound = max(25.0, 2.0 * noise_pct + 5.0)
        eps_ms = max(2.0, 0.10 * p50_unprot)
        assert over_pct < bound or over_delta_ms < eps_ms, \
            (f"admission costs {over_pct:.2f}% p50 at 1x "
             f"(bound {bound:.2f}%, floor {noise_pct:.2f}%)")
    else:
        assert prot_4x >= 0.7 * capacity_qps, \
            (f"protected goodput {prot_4x} < 70% of capacity "
             f"{capacity_qps:.1f} at 4x")
        assert unprot_4x < 0.7 * capacity_qps, \
            (f"unprotected leg did not collapse ({unprot_4x} vs "
             f"capacity {capacity_qps:.1f}) — the A/B proves nothing")
        bound = max(2.0, noise_pct + 1.0)
        assert over_pct < bound or over_delta_ms < 0.5, \
            (f"admission costs {over_pct:.2f}% p50 at 1x "
             f"(bound {bound:.2f}%, A/A floor {noise_pct:.2f}%)")


# ---------------------------------------------------------------------------
# --logs: CLP log-analytics workload (ISSUE 17)
# ---------------------------------------------------------------------------

_LOG_TEMPLATES = (
    lambda r: f"INFO  request req-{int(r.integers(0, 10**6))} served in "
              f"{int(r.integers(1, 500))} ms from host h{int(r.integers(0, 8))}",
    lambda r: f"WARN  GC pause of {round(float(r.random()) * 4, 2)} seconds "
              f"detected at offset {int(r.integers(0, 10**9))}",
    lambda r: f"ERROR Connection to 10.0.{int(r.integers(0, 32))}."
              f"{int(r.integers(1, 255))}:{int(r.integers(1000, 9000))} "
              f"refused after {int(r.integers(1, 6))} retries",
    lambda r: f"INFO  user u{int(r.integers(0, 500))} logged in from "
              f"10.1.{int(r.integers(0, 32))}.{int(r.integers(1, 255))}",
    lambda r: f"ERROR task t{int(r.integers(0, 9999))} failed on host "
              f"h{int(r.integers(0, 8))}: code={int(r.integers(400, 600))}",
    lambda r: f"WARN  disk /dev/sd{chr(97 + int(r.integers(0, 4)))}1 at "
              f"{int(r.integers(1, 99))}% capacity",
)


def _log_corpus(rng, n):
    k = len(_LOG_TEMPLATES)
    return [_LOG_TEMPLATES[int(rng.integers(0, k))](rng) for _ in range(n)]


def logs_main(smoke: bool = False, out_path: "str | None" = None):
    """--logs [--smoke]: the CLP log-analytics acceptance driver
    (ISSUE 17). Four legs over a realistic templated log corpus:

    * pushdown A/B — the SAME LIKE queries through the device CLP
      pushdown leg (logtype/dict/encoded-var match kernels over staged
      int32 pseudo-columns, no string decode) and through the host
      decode path; every answer parity-checked bit-exact, p50 ratio
      reported. Gate: device >= 2x host on the CPU stand-in (>= 5x on
      accelerators) — the host path pays string matching over the
      decoded column, the device path reads fixed-width ids.
    * coalesce — N clients loop fingerprint-equal LIKE queries whose
      pattern CONSTANTS differ (patterns live in staged params, never
      in the plan): batched launches must form with ZERO steady-state
      retraces once the pow2 shape buckets are warm.
    * ingest — realtime log ingestion into the mutable CLP column
      (template dictionary built AT INGEST, not at seal), sustained
      events/s with >= 2 seal rotations and exactly-once visibility,
      then a seeded SimulatedCrash (`ingest.realtime.consume`) killing
      the consumer MID-BATCH: a fresh manager recovers from the
      committed offset + sealed segments and converges to exactly-once
      (COUNT and SUM(ts) both exact) with ZERO failed queries.
    * mixed tenants — one MiniCluster serving an OLAP table (tenant
      weight 4) and the log table (weight 1) through the PR-8/15
      weighted-fair + brownout broker stack: the OLAP fleet's p99
      during mixed traffic must stay within its SLO target.

    Writes BENCH_logs.json (backend-gated bars). --smoke shrinks
    corpus/windows to fit tier-1 (tests/test_clp_device.py).
    """
    import contextlib
    import statistics as stats
    import tempfile
    import threading

    import jax

    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.ingest.memory_stream import InMemoryStream
    from pinot_tpu.ingest.realtime_manager import RealtimeSegmentDataManager
    from pinot_tpu.ingest.stream import LongMsgOffset, StreamConfig
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.ops import dispatch as dispatch_mod
    from pinot_tpu.ops import kernels
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment import index_types as seg_it
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.server.data_manager import TableDataManager
    from pinot_tpu.utils.config import PinotConfiguration
    from pinot_tpu.utils.failpoints import SimulatedCrash, failpoints

    on_cpu = jax.devices()[0].platform == "cpu"
    if smoke:
        docs, num_segments, p50_iters = 1_500, 2, 6
        clients, window_s = 6, 0.8
        max_events, flush_rows = 4_000, 600
        chaos_events, chaos_flush = 2_500, 400
        mix_window_s, olap_clients, log_clients = 1.0, 3, 3
    else:
        docs, num_segments, p50_iters = 25_000, 4, 30
        clients, window_s = 8, 2.5
        max_events, flush_rows = 60_000, 8_000
        chaos_events, chaos_flush = 20_000, 3_000
        mix_window_s, olap_clients, log_clients = 4.0, 4, 4

    tmp = tempfile.mkdtemp(prefix="bench_logs_")
    schema = Schema("logs", [
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("message", DataType.STRING),
    ])
    tc = TableConfig("logs", TableType.OFFLINE)
    tc.indexing.clp_columns = ["message"]
    segs, raw_bytes, clp_bytes = [], 0, 0
    for i in range(num_segments):
        rng = np.random.default_rng(1700 + i)
        msgs = _log_corpus(rng, docs)
        out_dir = os.path.join(tmp, f"logs_{i}")
        SegmentCreator(tc, schema).build(
            {"ts": np.arange(docs, dtype=np.int64), "message": msgs},
            out_dir, f"logs_{i}")
        seg = load_segment(out_dir)
        segs.append(seg)
        raw_bytes += sum(len(m.encode()) for m in msgs)
        clp_bytes += len(bytes(seg.dir.get_buffer("message", seg_it.CLP)))

    labels = {"bench_leg": "logs"}
    eng = TpuOperatorExecutor(config=PinotConfiguration(),
                              metrics_labels=labels)
    reg = eng._dispatcher._metrics
    dev = QueryExecutor(segs, use_tpu=True, engine=eng)
    host = QueryExecutor(segs, use_tpu=False)

    # ------------------------------------------------------------------
    # leg 1: pushdown A/B — parity + p50 ratio
    # ------------------------------------------------------------------
    needles = ["%refused%", "%failed on host%", "INFO%", "%capacity",
               "%logged in%"]
    sqls = [f"SELECT COUNT(*) FROM logs WHERE message LIKE '{p}'"
            for p in needles]
    served0 = reg.meter("clp_served", labels=labels)
    for sql in sqls:
        a, b = dev.execute(sql), host.execute(sql)
        assert not a.exceptions and not b.exceptions, sql
        assert a.result_table.rows[0][0] == b.result_table.rows[0][0], \
            (sql, a.result_table.rows, b.result_table.rows)
    served = reg.meter("clp_served", labels=labels) - served0
    assert served == len(sqls), \
        f"only {served}/{len(sqls)} LIKE queries served device-side"

    def p50(ex, sql):
        lat = []
        for _ in range(p50_iters):
            t0 = time.perf_counter()
            ex.execute(sql)
            lat.append((time.perf_counter() - t0) * 1e3)
        return stats.median(lat)

    ab = {}
    for p, sql in zip(needles[:3], sqls[:3]):
        d, h = p50(dev, sql), p50(host, sql)
        ab[p] = {"device_p50_ms": round(d, 3), "host_p50_ms": round(h, 3),
                 "speedup": round(h / max(d, 1e-9), 2)}
    speedup_min = min(v["speedup"] for v in ab.values())

    # ------------------------------------------------------------------
    # leg 2: coalesce — constant-different LIKE queries, zero retraces
    # ------------------------------------------------------------------
    coal_sqls = [f"SELECT COUNT(*) FROM logs WHERE message LIKE "
                 f"'%failed on host h{i % 8}:%'" for i in range(clients)]
    for sql in coal_sqls:   # stage blocks + params, trace b=1
        assert not dev.execute(sql).exceptions
    launch = eng._prepare_agg(
        segs, QueryContext.from_sql(coal_sqls[0]))[3]
    guard = dispatch_mod._CPU_COLLECTIVE_LOCK if launch.collective \
        else contextlib.nullcontext()
    b = 2
    while b <= dispatch_mod._pow2(clients):  # warm pow2 batch buckets
        kern = launch.factory(b, False)
        with guard:
            jax.block_until_ready(kern(
                launch.cols, (launch.params,) * b, launch.num_docs,
                D=launch.D, G=launch.G))
        b *= 2
    traces0 = kernels.trace_count()
    batch_t0 = reg.timer("dispatch_batch_size", labels=labels)
    count0, max0 = batch_t0.count, batch_t0.max_ms
    stop_at = time.perf_counter() + window_s
    done = [0] * clients

    def coal_client(ci):
        j = 0
        while time.perf_counter() < stop_at:
            dev.execute(coal_sqls[(ci + j) % clients])
            done[ci] += 1
            j += 1

    threads = [threading.Thread(target=coal_client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    batch_t = reg.timer("dispatch_batch_size", labels=labels)
    coalesce = {
        "clients": clients,
        "queries_completed": int(sum(done)),
        "qps": round(sum(done) / wall, 2),
        "batch_launches": batch_t.count - count0,
        "batch_size_max": max(batch_t.max_ms, max0),
        "retraces_steady": kernels.trace_count() - traces0,
    }

    # ------------------------------------------------------------------
    # leg 3: realtime ingest — events/s, then seeded mid-batch kill
    # ------------------------------------------------------------------
    def rt_cfg():
        c = TableConfig("logs", TableType.REALTIME)
        c.indexing.clp_columns = ["message"]
        return c

    def query_fleet(serving, stop_evt, n_clients, sql_of):
        lats, fails = [], []
        lock = threading.Lock()

        def client(ci):
            i = ci
            while not stop_evt.is_set():
                i += 1
                t0 = time.time()
                try:
                    tdm = serving["tdm"]
                    sdms = tdm.acquire_segments()
                    try:
                        r = QueryExecutor(
                            [s.segment for s in sdms],
                            use_tpu=False).execute(sql_of(i))
                        if r.exceptions:
                            raise RuntimeError(str(r.exceptions[:1]))
                    finally:
                        TableDataManager.release_all(sdms)
                    with lock:
                        lats.append(time.time() - t0)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        fails.append(repr(e))
        ts = [threading.Thread(target=client, args=(ci,))
              for ci in range(n_clients)]
        for t in ts:
            t.start()
        return ts, lats, fails

    log_sql = "SELECT COUNT(*) FROM logs WHERE message LIKE '%refused%'"

    # -- 3a: sustained throughput + exactly-once at rest ---------------
    topic = InMemoryStream("bench_logs_ingest", 1)
    store = tempfile.mkdtemp(prefix="bench_logs_rt_")
    tdm = TableDataManager("logs_REALTIME")
    commits = []
    rng = np.random.default_rng(77)
    mgr = RealtimeSegmentDataManager(
        rt_cfg(), schema, StreamConfig(
            stream_type="inmemory", topic="bench_logs_ingest",
            flush_threshold_rows=flush_rows),
        0, tdm, store, on_commit=lambda n, o: commits.append((n, o)))
    for i in range(max_events):  # pre-published deterministic log
        topic.publish({"ts": i, "message": _log_corpus(rng, 1)[0]})
    stop_evt = threading.Event()
    fleet, lats, fails = query_fleet(
        {"tdm": tdm}, stop_evt, 2, lambda i: log_sql)
    t_start = time.time()
    mgr.start()
    deadline = time.time() + 300
    while time.time() < deadline and mgr.rows_indexed < max_events:
        time.sleep(0.02)
    elapsed = time.time() - t_start
    stop_evt.set()
    for t in fleet:
        t.join(timeout=10)
    drained = mgr.rows_indexed
    mgr.stop(drain=True)
    events_per_sec = drained / max(elapsed, 1e-9)
    sdms = tdm.acquire_segments()
    try:
        r = QueryExecutor([s.segment for s in sdms],
                          use_tpu=False).execute(
            "SELECT COUNT(*), SUM(ts) FROM logs LIMIT 5")
        exact = (int(r.rows[0][0]), float(r.rows[0][1]))
    finally:
        TableDataManager.release_all(sdms)
    want = (max_events, float(max_events * (max_events - 1) // 2))
    InMemoryStream.delete("bench_logs_ingest")

    # -- 3b: seeded mid-batch kill -> restart -> exactly-once ----------
    topic3 = InMemoryStream("bench_logs_chaos", 1)
    store3 = tempfile.mkdtemp(prefix="bench_logs_chaos_")
    tdm3 = TableDataManager("logs_REALTIME")
    commits3 = []
    rng3 = np.random.default_rng(88)
    for i in range(chaos_events):
        topic3.publish({"ts": i, "message": _log_corpus(rng3, 1)[0]})
    # probability tuned so the seeded kill lands MID-STREAM: the full
    # run has ~200 fetch hits, so p=0.01 fires deep enough that sealed
    # segments exist to recover; smoke's 25 hits need a hotter trigger
    fp = failpoints.arm("ingest.realtime.consume",
                        error=SimulatedCrash("kill"), times=1,
                        probability=0.05 if smoke else 0.01,
                        seed=20260807)
    sc3 = StreamConfig(stream_type="inmemory", topic="bench_logs_chaos",
                       flush_threshold_rows=chaos_flush)
    m3 = RealtimeSegmentDataManager(
        rt_cfg(), schema, sc3, 0, tdm3, store3,
        on_commit=lambda n, o: commits3.append((n, o)))
    serving = {"tdm": tdm3}
    stop3 = threading.Event()
    fleet3, lats3, fails3 = query_fleet(serving, stop3, 2,
                                        lambda i: log_sql)
    m3.start()
    deadline = time.time() + 120
    while time.time() < deadline and not m3._crashed:
        time.sleep(0.01)
    crashed = m3._crashed
    m3.stop()  # joins the dead thread
    # restart exactly as a fresh server process would: committed offset
    # + sealed segments from the store; the crashed mutable VANISHES
    resume = max((int(str(o)) for _n, o in commits3), default=0)
    tdm4 = TableDataManager("logs_REALTIME")
    recovered = []
    for nm in sorted(os.listdir(store3)):
        path = os.path.join(store3, nm)
        if os.path.isdir(path) and not nm.startswith("_"):
            seg = load_segment(path)
            tdm4.add_segment(seg)
            recovered.append(seg)
    m4 = RealtimeSegmentDataManager(
        rt_cfg(), schema, sc3, 0, tdm4, store3,
        start_offset=LongMsgOffset(resume), start_seq=len(recovered),
        recover_segments=recovered)
    m4.start()
    serving["tdm"] = tdm4  # queries swap to the recovered view
    chaos_want = (chaos_events,
                  float(chaos_events * (chaos_events - 1) // 2))
    chaos_got = (None, None)
    deadline = time.time() + 180
    while time.time() < deadline:
        sdms = tdm4.acquire_segments()
        try:
            r = QueryExecutor([s.segment for s in sdms],
                              use_tpu=False).execute(
                "SELECT COUNT(*), SUM(ts) FROM logs LIMIT 5")
        finally:
            TableDataManager.release_all(sdms)
        if not r.exceptions:
            chaos_got = (int(r.rows[0][0]), float(r.rows[0][1]))
            if chaos_got == chaos_want:
                break
        time.sleep(0.05)
    stop3.set()
    for t in fleet3:
        t.join(timeout=10)
    m4.stop(drain=True)
    decisions = list(fp.decisions)
    failpoints.disarm("ingest.realtime.consume")
    InMemoryStream.delete("bench_logs_chaos")

    # ------------------------------------------------------------------
    # leg 4: mixed tenants — OLAP p99 within SLO under log traffic
    # ------------------------------------------------------------------
    slo_ms = 400.0 if on_cpu else 100.0
    olap_schema = Schema("ssb", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    olap_creator = SegmentCreator(TableConfig("ssb", TableType.OFFLINE),
                                  olap_schema)
    c = MiniCluster(num_servers=1, config=PinotConfiguration(overrides={
        "pinot.slo.query.p99.ms": slo_ms}))
    c.start()
    c.add_table("ssb", tenant="olap", tenant_weight=4.0)
    c.add_table("logs", tenant="logs", tenant_weight=1.0)
    for i in range(2):
        rngo = np.random.default_rng(40 + i)
        d = os.path.join(tmp, f"ssb_{i}")
        olap_creator.build(
            {"k": rngo.integers(0, 1000, 4000).astype(np.int32),
             "v": rngo.integers(0, 100, 4000).astype(np.int32)},
            d, f"ssb_{i}")
        c.add_segment("ssb", load_segment(d), server_idx=0)
    for seg in segs[:2]:
        c.add_segment("logs", seg, server_idx=0)
    olap_sql = ("SELECT SUM(v), COUNT(*) FROM ssb "
                "WHERE k BETWEEN 100 AND 800 OPTION(skipCache=true)")

    def mix_window(with_logs):
        stop_m = threading.Event()
        olap_lat, log_lat, mfails = [], [], []
        lock = threading.Lock()

        def olap_client():
            while not stop_m.is_set():
                t0 = time.perf_counter()
                r = c.query(olap_sql)
                dt = (time.perf_counter() - t0) * 1e3
                with lock:
                    if r.exceptions:
                        mfails.append(str(r.exceptions[:1]))
                    else:
                        olap_lat.append(dt)

        def log_client(ci):
            j = ci
            while not stop_m.is_set():
                j += 1
                t0 = time.perf_counter()
                r = c.query("SELECT COUNT(*) FROM logs WHERE message "
                            f"LIKE '%failed on host h{j % 8}:%' "
                            "OPTION(skipCache=true)")
                dt = (time.perf_counter() - t0) * 1e3
                with lock:
                    if r.exceptions:
                        mfails.append(str(r.exceptions[:1]))
                    else:
                        log_lat.append(dt)

        ts = [threading.Thread(target=olap_client)
              for _ in range(olap_clients)]
        if with_logs:
            ts += [threading.Thread(target=log_client, args=(i,))
                   for i in range(log_clients)]
        for t in ts:
            t.start()
        time.sleep(mix_window_s)
        stop_m.set()
        for t in ts:
            t.join(timeout=10)
        return olap_lat, log_lat, mfails

    c.query(olap_sql)  # warm both paths before measuring
    c.query("SELECT COUNT(*) FROM logs WHERE message LIKE '%refused%'")
    iso_lat, _, iso_fails = mix_window(with_logs=False)
    mixed_lat, mixed_log_lat, mixed_fails = mix_window(with_logs=True)
    c.stop()
    mixed = {
        "slo_p99_ms": slo_ms,
        "olap_tenant_weight": 4.0,
        "logs_tenant_weight": 1.0,
        "olap_iso_p50_ms": round(_pct(0.50, iso_lat), 2),
        "olap_iso_p99_ms": round(_pct(0.99, iso_lat), 2),
        "olap_mixed_p50_ms": round(_pct(0.50, mixed_lat), 2),
        "olap_mixed_p99_ms": round(_pct(0.99, mixed_lat), 2),
        "log_mixed_p50_ms": round(_pct(0.50, mixed_log_lat), 2),
        "olap_queries": len(iso_lat) + len(mixed_lat),
        "log_queries": len(mixed_log_lat),
        "failed_queries": len(iso_fails) + len(mixed_fails),
    }

    out = {
        "metric": "clp_device_like_speedup_vs_host_decode",
        "value": speedup_min,
        "unit": "x",
        "docs": num_segments * docs,
        "clp_compression_ratio": round(raw_bytes / max(clp_bytes, 1), 2),
        "pushdown_ab": ab,
        "clp_served": int(served),
        "coalesce": coalesce,
        "ingest": {
            "events_per_sec": round(events_per_sec),
            "events_published": max_events,
            "events_indexed": int(drained),
            "seals": len(commits),
            "exact": [list(exact), list(want)],
            "query_p50_ms": round(_pct(0.50, lats) * 1e3, 2),
            "failed_queries": len(fails),
        },
        "chaos": {
            "crashed": bool(crashed),
            "converged": chaos_got == chaos_want,
            "got": list(chaos_got),
            "want": list(chaos_want),
            "seals_before_kill": len(commits3),
            "resume_offset": resume,
            "decisions": len(decisions),
            "failed_queries": len(fails3),
        },
        "mixed_tenants": mixed,
        "host_cpu_cores": os.cpu_count(),
        "backend": jax.devices()[0].platform,
        "smoke": smoke,
        "asserted": {
            "parity": "device LIKE == host LIKE, bit-exact counts",
            "min_speedup": 2.0 if on_cpu else 5.0,
            "max_steady_retraces": 0,
            "min_batch_size": 2,
            "exactly_once": True,
            "olap_p99_within_slo": True,
            "failed_queries": 0,
        },
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_logs.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))

    # -- gates ---------------------------------------------------------
    assert coalesce["retraces_steady"] == 0, \
        f"steady-state retraces: {coalesce['retraces_steady']}"
    assert coalesce["batch_size_max"] >= 2, \
        "fingerprint-equal CLP queries never coalesced"
    assert drained == max_events and exact == want, (exact, want)
    assert len(commits) >= 2, "no seal rotations — widen the window"
    assert len(fails) == 0, f"ingest-window queries failed: {fails[:3]}"
    assert crashed, "chaos never fired"
    assert chaos_got == chaos_want, (chaos_got, chaos_want)
    assert len(fails3) == 0, f"chaos-window queries failed: {fails3[:3]}"
    assert mixed["failed_queries"] == 0, "mixed-traffic queries failed"
    if not smoke:
        gate = 2.0 if on_cpu else 5.0
        assert speedup_min >= gate, \
            f"device LIKE speedup {speedup_min}x under the {gate}x bar"
        assert mixed["olap_mixed_p99_ms"] <= slo_ms, \
            (f"OLAP p99 {mixed['olap_mixed_p99_ms']}ms broke the "
             f"{slo_ms}ms SLO under mixed traffic")


def _rebalance_build_cluster(tmp: str, num_segments: int, docs: int):
    """3 servers, replication 2: every segment lives on servers 0 and 1,
    server 2 is empty — the rebalance target and the repair headroom.
    Returns (cluster, segment_names, expected_answers) where
    expected_answers[k] = (count, sum) for ``WHERE k >= k``."""
    import numpy as np

    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    schema = Schema.from_dict({
        "schemaName": "rb",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
    tc = TableConfig.from_dict(
        {"tableName": "rb", "tableType": "OFFLINE",
         "segmentsConfig": {"replication": 2}})
    creator = SegmentCreator(tc, schema)
    # roomy retry budget: when a server is killed mid-loop, all 8
    # clients' in-flight queries retry at once — availability, not
    # retry-storm damping, is what this bench measures
    cfg = PinotConfiguration().with_overrides(
        {"pinot.broker.retry.budget.min": 64.0,
         "pinot.broker.retry.budget.cap": 256.0})
    cluster = MiniCluster(num_servers=3, config=cfg)
    cluster.start()
    cluster.add_table("rb", table_config=tc, schema=schema)
    ks, vs, names = [], [], []
    for i in range(num_segments):
        rng = np.random.default_rng(300 + i)
        k = rng.integers(0, 8, docs).astype(np.int64)
        v = rng.integers(0, 1000, docs).astype(np.int64)
        d = os.path.join(tmp, f"rb_{i}")
        creator.build({"k": k, "v": v}, d, f"rb_{i}")
        seg = load_segment(d)
        cluster.add_segment("rb", seg, server_idx=i % 2,
                            replicas=[(i + 1) % 2])
        ks.append(k)
        vs.append(v)
        names.append(seg.name)
    k = np.concatenate(ks)
    v = np.concatenate(vs)
    expected = {kk: (int((k >= kk).sum()), int(v[k >= kk].sum()))
                for kk in range(5)}
    return cluster, names, expected


def _rebalance_chaos_journal(tmp: str, sub: str, seed: int,
                             num_segments: int):
    """One seeded chaos run of a pure-state rebalance plan (engine only,
    max.parallel.moves=1): returns (journal sha1, failpoint decisions).
    Two same-seed runs must match byte for byte."""
    import hashlib

    from pinot_tpu.controller.cluster_state import (
        ClusterState, InstanceState, SegmentState)
    from pinot_tpu.controller.rebalancer import Rebalancer
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.utils.config import PinotConfiguration
    from pinot_tpu.utils.failpoints import FaultSchedule
    from pinot_tpu.utils.metrics import MetricsRegistry

    st = ClusterState()
    for i in range(3):
        st.register_instance(InstanceState(f"server_{i}"))
    st.add_table(
        TableConfig.from_dict({"tableName": "rb", "tableType": "OFFLINE"}),
        Schema.from_dict({"schemaName": "rb", "dimensionFieldSpecs":
                          [{"name": "k", "dataType": "LONG"}]}))
    for i in range(num_segments):
        st.upsert_segment(SegmentState(f"rb_{i}", "rb_OFFLINE",
                                       [f"server_{i % 2}"],
                                       dir_path=f"/deep/rb_{i}"))
    jp = os.path.join(tmp, f"chaos_{sub}.journal")
    rb = Rebalancer(
        st, load_fn=lambda *a: None, unload_fn=lambda *a: None,
        config=PinotConfiguration().with_overrides(
            {"pinot.controller.rebalance.max.parallel.moves": 1}),
        journal_path=jp, metrics=MetricsRegistry("controller"))
    sched = FaultSchedule([
        ("controller.rebalance.move",
         {"delay": 0.002, "probability": 0.5, "seed": seed}),
    ])
    sched.arm()
    try:
        job = rb.run("rb_OFFLINE", {
            f"rb_{i}": {"from": [f"server_{i % 2}"],
                        "to": [f"server_{(i + 1) % 3}"]}
            for i in range(num_segments)})
    finally:
        sched.disarm()
        rb.close()
    assert job.status == "DONE", job.progress()
    with open(jp, "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()
    return digest, sched.decisions()


def rebalance_main(smoke: bool = False, out_path: "str | None" = None):
    """--rebalance [--smoke]: self-healing acceptance (ISSUE 18).

    Leg A — **live rebalance, zero downtime**: an 8-client closed loop
    runs while EVERY segment moves from servers {0,1} to {1,2} through
    the journaled move engine (load+warm target -> one batched
    assignment/routing commit -> drain source, never below the
    availability floor). Asserts zero failed queries, zero wrong
    answers (a query routed to an unloaded target, or a source drained
    early, would return silently short rows), and a commit-time guard
    that every instance in the new assignment already holds its
    segment (the flip-before-load regression the one-shot assignment
    flip had).

    Leg B — **kill + automatic repair**: server 1 is killed
    (SIGKILL-equivalent) mid-loop; the RepairChecker debounces the dead
    heartbeat (two stale ticks), re-replicates its segments from their
    dirs onto the surviving server through the same move engine, and
    `segments_missing_replicas` drains to 0. Asserts zero failed
    queries (broker failover bridges the gap) and repair convergence.

    Leg C — **seeded chaos determinism**: the same plan under a seeded
    delay schedule at `controller.rebalance.move` (parallelism 1) runs
    twice; move journals must be byte-identical and the failpoint
    decision logs equal.

    Writes BENCH_rebalance.json. --smoke shrinks data + durations and
    skips the throughput-floor assert; zero-failures, correctness,
    convergence, and replay-identical are asserted always."""
    import tempfile
    import threading

    from pinot_tpu.utils.metrics import MetricsRegistry

    num_segments = 4 if smoke else 8
    docs = 800 if smoke else 20_000
    duration_s = 1.2 if smoke else 5.0
    clients = 8

    tmp = tempfile.mkdtemp(prefix="bench_rebalance_")
    cluster, seg_names, expected = _rebalance_build_cluster(
        tmp, num_segments, docs)

    lock = threading.Lock()

    def closed_loop(duration: float):
        """8-client closed loop; returns (latencies, failures, wrong)."""
        stop_at = time.perf_counter() + duration
        lat, failures, wrong = [], [], []

        def client(cid: int):
            i = cid
            while time.perf_counter() < stop_at:
                kk = i % 5
                t0 = time.perf_counter()
                resp = cluster.query(
                    f"SELECT COUNT(*), SUM(v) FROM rb WHERE k >= {kk}")
                dt = time.perf_counter() - t0
                with lock:
                    lat.append(dt)
                    if resp.exceptions:
                        failures.append(resp.exceptions)
                    elif (resp.rows[0][0], resp.rows[0][1]) != expected[kk]:
                        wrong.append((kk, resp.rows[0], expected[kk]))
                i += clients
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return lat, failures, wrong

    def p(q, vals):
        if not vals:
            return 0.0
        return sorted(vals)[min(len(vals) - 1,
                                max(0, round(q * len(vals)) - 1))]

    for i in range(4):  # warm parse/plan/serde
        resp = cluster.query(f"SELECT COUNT(*), SUM(v) FROM rb "
                             f"WHERE k >= {i % 5}")
        assert not resp.exceptions, resp.exceptions

    lat_base, fail_base, wrong_base = closed_loop(duration_s)
    qps_base = len(lat_base) / duration_s

    # -- leg A: live rebalance under load ------------------------------
    rb = cluster.make_rebalancer(
        journal_path=os.path.join(tmp, "rebalance.journal"))
    inner_commit = rb.commit_fn
    guard_violations = []

    def checked_commit(table, assignment):
        # flip-before-load guard: at commit time, EVERY instance in the
        # new assignment must already hold the segment (loaded+warmed)
        for name, insts in assignment.items():
            for iid in insts:
                srv = next(s for s in cluster.servers
                           if s.instance_id == iid)
                tdm = srv.data_manager.table(table, create=False)
                if tdm is None or tdm.current_segment(name) is None:
                    guard_violations.append((name, iid))
        inner_commit(table, assignment)

    rb.commit_fn = checked_commit
    move_result = {}

    def run_move():
        try:
            job = rb.run("rb_OFFLINE", {
                name: {"from": ["server_0", "server_1"],
                       "to": ["server_1", "server_2"]}
                for name in seg_names})
            move_result["status"] = job.status
            move_result["moves_done"] = job.progress()["done"]
        except Exception as exc:  # noqa: BLE001 — surface, don't hang
            move_result["status"] = f"error: {exc!r}"

    mover = threading.Timer(duration_s * 0.25, run_move)
    mover.start()
    lat_move, fail_move, wrong_move = closed_loop(duration_s)
    mover.join()
    qps_move = len(lat_move) / duration_s
    drained = all(
        cluster.servers[0].data_manager.table(
            "rb_OFFLINE").current_segment(n) is None for n in seg_names)

    # -- leg B: kill server_1 + automatic repair under load ------------
    reg = MetricsRegistry("controller")
    rb.metrics = reg
    rep = cluster.make_repair_checker(rb)
    rep.metrics = reg
    rep.grace_s = 0.02
    repair_result = {"converged": False, "ticks": 0,
                     "convergence_s": None}

    def kill_and_repair():
        time.sleep(duration_s * 0.25)
        t_kill = time.perf_counter()
        cluster.kill_server(1)
        deadline = time.perf_counter() + max(duration_s * 4, 20.0)
        while time.perf_counter() < deadline:
            out = rep.check_once()
            repair_result["ticks"] += 1
            missing = reg.sample()["gauges"].get(
                'segments_missing_replicas{table="rb_OFFLINE"}')
            if out["stale"] and out["repaired"] == {} and missing == 0:
                repair_result["converged"] = True
                repair_result["convergence_s"] = round(
                    time.perf_counter() - t_kill, 3)
                return
            time.sleep(0.03)

    repairer = threading.Thread(target=kill_and_repair)
    repairer.start()
    lat_kill, fail_kill, wrong_kill = closed_loop(duration_s)
    repairer.join()
    qps_kill = len(lat_kill) / duration_s
    rb.close()
    cluster.stop()

    # -- leg C: same-seed chaos -> byte-identical journals -------------
    seed = 20260807
    dig_a, dec_a = _rebalance_chaos_journal(tmp, "a", seed, num_segments)
    dig_b, dec_b = _rebalance_chaos_journal(tmp, "b", seed, num_segments)
    journals_identical = dig_a == dig_b and dec_a == dec_b

    out = {
        "metric": "self_healing_failed_queries",
        "value": len(fail_move) + len(fail_kill),
        "unit": "queries",
        "rebalance": {
            "failed_queries": len(fail_move),
            "wrong_answers": len(wrong_move),
            "guard_violations": len(guard_violations),
            "job_status": move_result.get("status"),
            "moves_done": move_result.get("moves_done"),
            "sources_drained": drained,
            "qps_during_move": round(qps_move, 1),
            "p99_during_move_ms": round(p(0.99, lat_move) * 1e3, 2),
        },
        "repair": {
            "failed_queries": len(fail_kill),
            "wrong_answers": len(wrong_kill),
            "converged": repair_result["converged"],
            "convergence_s": repair_result["convergence_s"],
            "repair_ticks": repair_result["ticks"],
            "qps_during_kill_repair": round(qps_kill, 1),
            "p99_during_kill_repair_ms": round(p(0.99, lat_kill) * 1e3, 2),
        },
        "determinism": {
            "journals_identical": journals_identical,
            "journal_digest": dig_a[:16],
        },
        "baseline": {
            "failed_queries": len(fail_base),
            "wrong_answers": len(wrong_base),
            "qps": round(qps_base, 1),
            "p50_ms": round(p(0.50, lat_base) * 1e3, 2),
            "p99_ms": round(p(0.99, lat_base) * 1e3, 2),
        },
        "queries_total": len(lat_base) + len(lat_move) + len(lat_kill),
        "num_segments": num_segments,
        "docs_per_segment": docs,
        "clients": clients,
        "smoke": smoke,
        "asserted": {"failed_queries": 0, "wrong_answers": 0,
                     "guard_violations": 0, "converged": True,
                     "journals_identical": True,
                     "min_qps_frac": None if smoke else 0.25},
    }
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_rebalance.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    assert move_result.get("status") == "DONE", move_result
    assert not guard_violations, \
        f"routing flipped before load: {guard_violations[:3]}"
    assert not fail_base and not fail_move and not fail_kill, \
        (f"failed queries: base={len(fail_base)} move={len(fail_move)} "
         f"kill={len(fail_kill)}: "
         f"{(fail_base + fail_move + fail_kill)[:3]}")
    assert not wrong_base and not wrong_move and not wrong_kill, \
        (f"wrong answers: {wrong_base[:2]} {wrong_move[:2]} "
         f"{wrong_kill[:2]}")
    assert drained, "sources not drained after the move"
    assert repair_result["converged"], \
        f"repair did not converge: {repair_result}"
    assert journals_identical, "same-seed chaos journals diverged"
    if not smoke:
        assert qps_move >= 0.25 * qps_base, \
            f"rebalance collapsed throughput: {qps_move:.0f} vs " \
            f"{qps_base:.0f} baseline QPS"
        assert qps_kill >= 0.25 * qps_base, \
            f"kill+repair collapsed throughput: {qps_kill:.0f} vs " \
            f"{qps_base:.0f} baseline QPS"


def _mesh_build_table(tmp, name, num_segments, docs, seed):
    """SSB-Q1.1-shaped table (same column mix as the batching bench):
    dict dims + a raw metric, integer-valued so the merged path's sums
    are bit-exact against the host fold."""
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    schema = Schema(name, [
        FieldSpec("lo_orderdate", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_discount", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_quantity", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_extendedprice", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig(name, TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["lo_extendedprice"]
    tc.indexing.compression = "PASS_THROUGH"
    creator = SegmentCreator(tc, schema)
    dates = np.array([y * 10000 + m * 100 + d
                      for y in range(1992, 1999)
                      for m in range(1, 13) for d in range(1, 29)],
                     dtype=np.int32)
    segs = []
    for i in range(num_segments):
        rng = np.random.default_rng(seed + i)
        out = os.path.join(tmp, f"{name}_{i}")
        creator.build({
            "lo_orderdate": dates[rng.integers(0, len(dates), docs)],
            "lo_discount": rng.integers(0, 11, docs).astype(np.int32),
            "lo_quantity": rng.integers(1, 51, docs).astype(np.int32),
            # small ints: every grouped f32 partial sum stays under
            # 2^24, so merged-vs-host parity is EXACT equality even in
            # f32 staging (the non-grouped SUM is isum-plane exact
            # regardless of magnitude)
            "lo_extendedprice": rng.integers(1, 500, docs).astype(np.int32),
        }, out, f"{name}_{i}")
        segs.append(load_segment(out))
    return segs


_MESH_SQLS = (
    # SSB Q1.1: range filters + SUM of product + COUNT — the isum plane
    # makes the SUM bit-exact, so merged-vs-host parity is == not ~=
    "SELECT SUM(lo_extendedprice * lo_discount), COUNT(*) FROM {t} "
    "WHERE lo_orderdate BETWEEN 19940101 AND 19940631 "
    "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25",
    # group-by with min/max: the merged kernel's pmin/pmax semiring plus
    # the host-side global-key factorization
    "SELECT lo_discount, SUM(lo_extendedprice), MIN(lo_quantity), "
    "MAX(lo_quantity), COUNT(*) FROM {t} GROUP BY lo_discount "
    "ORDER BY lo_discount LIMIT 20",
)


def _mesh_measure(engine_on, engine_off, segs, table, total_docs,
                  rounds, window_s, p50_iters, labels_on):
    """One paired merge-ON vs merge-OFF A/B at a fixed mesh size —
    the BENCH_batching discipline: alternating back-to-back windows,
    per-round paired ratios (median cancels box drift), interleaved
    single-query p50, steady-state retrace delta asserted zero."""
    import statistics as stats

    from pinot_tpu.ops import kernels
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.executor import QueryExecutor

    ex_on = QueryExecutor(segs, use_tpu=True, engine=engine_on)
    ex_off = QueryExecutor(segs, use_tpu=True, engine=engine_off)
    ctxs = [QueryContext.from_sql(q.format(t=table)) for q in _MESH_SQLS]

    # warm: compile every (plan, mesh) shape both modes will run, and
    # assert the merged path answers BIT-IDENTICALLY to the host fold
    # (integer data: the isum plane and exact group counts make ==
    # legitimate, not a tolerance check)
    for sql in (q.format(t=table) for q in _MESH_SQLS):
        r_on = ex_on.execute(sql)
        r_off = ex_off.execute(sql)
        assert not r_on.exceptions and not r_off.exceptions, (
            r_on.exceptions, r_off.exceptions)
        assert r_on.rows == r_off.rows, (
            f"merged path diverged from host fold: {sql}: "
            f"{r_on.rows} vs {r_off.rows}")

    def one(ex, i):
        t0 = time.perf_counter()
        ex.execute_context(ctxs[i % len(ctxs)])
        return (time.perf_counter() - t0) * 1e3

    for i in range(4):  # settle caches on both paths
        one(ex_on, i), one(ex_off, i)
    traces0 = kernels.trace_count()

    lat_on, lat_off = [], []
    for i in range(p50_iters):
        if i % 2 == 0:
            lat_off.append(one(ex_off, i))
            lat_on.append(one(ex_on, i))
        else:
            lat_on.append(one(ex_on, i))
            lat_off.append(one(ex_off, i))

    def window(ex):
        n = 0
        t0 = time.perf_counter()
        stop_at = t0 + window_s
        while time.perf_counter() < stop_at:
            ex.execute_context(ctxs[n % len(ctxs)])
            n += 1
        return n, time.perf_counter() - t0

    on_n = on_wall = off_n = off_wall = 0.0
    ratios = []
    for r in range(rounds):
        order = [(ex_off, "off"), (ex_on, "on")] if r % 2 == 0 \
            else [(ex_on, "on"), (ex_off, "off")]
        qps = {}
        for ex, tag in order:
            n, w = window(ex)
            qps[tag] = n / w
            if tag == "on":
                on_n += n
                on_wall += w
            else:
                off_n += n
                off_wall += w
        ratios.append(qps["on"] / max(qps["off"], 1e-9))

    reg = engine_on._dispatcher._metrics
    return {
        "rows_per_sec": round(on_n * total_docs / on_wall),
        "rows_per_sec_hostfold": round(off_n * total_docs / off_wall),
        "merge_speedup": round(stats.median(ratios), 2),
        "p50_ms": round(stats.median(lat_on), 2),
        "p50_ms_hostfold": round(stats.median(lat_off), 2),
        "retraces_steady": kernels.trace_count() - traces0,
        "merge_served": int(reg.meter("mesh_merge_served",
                                      labels=labels_on)),
    }


def mesh_main(smoke: bool = False, out_path: "str | None" = None):
    """--mesh [--smoke]: measured multi-chip scaling (ISSUE 19).

    Two legs, both through PARSED SQL on (segments x docs) mesh engines
    with the collective broker merge ON, each paired A/B against the
    host-IndexedTable-fold escape hatch
    (`pinot.server.mesh.collective.merge=false`) in alternating
    back-to-back windows — the BENCH_batching discipline:

      segments_axis — weak scaling over 1 -> 2 -> 4 -> 8 devices with
        FIXED PER-CHIP data (segment count scales with the mesh, so
        each chip always holds the same bytes). Headline: rows/sec/chip
        efficiency vs the 1-device run. On real accelerators each chip
        adds its own HBM bandwidth, so efficiency >= 0.8 is the gate.
        The CPU stand-in's 8 "devices" share the same few cores — total
        work grows with the mesh while compute does not, so per-chip
        efficiency is structurally ~1/n there; the CPU gate is instead
        structural: TOTAL rows/s must hold (>= 0.5x the 1-device rate,
        i.e. sharding+collectives overhead stays bounded), every curve
        point is measured, and the merged path actually served.
      doc_axis — ONE huge segment sharded across the `docs` axis (the
        segments axis cannot help a single segment; this is the leg
        that motivates the second mesh dimension). Measured against the
        same segment on a 1-device engine.

    Every leg asserts zero steady-state retraces and that the merged
    rows are BIT-IDENTICAL to the host fold (integer data: isum plane).
    Writes BENCH_mesh.json. --smoke shrinks device counts, data, and
    windows to fit tier-1 (structural assertions only)."""
    import shutil
    import tempfile

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax: the XLA flag takes effect when the backend is not
        # yet initialized (no-op under pytest — conftest already forced
        # 8 virtual devices)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    except RuntimeError:
        pass  # backend already initialized (in-process smoke run)
    if len(jax.devices()) < 8:
        raise SystemExit("mesh bench needs 8 (virtual) devices")

    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.parallel.mesh import make_mesh
    from pinot_tpu.utils.config import PinotConfiguration

    counts = (1, 2) if smoke else (1, 2, 4, 8)
    segs_per_chip = 2 if smoke else 4
    docs = 1200 if smoke else 6000
    rounds = 2 if smoke else 4
    window_s = 0.5 if smoke else 2.5
    p50_iters = 8 if smoke else 30
    doc_leg_docs = 16_000 if smoke else 96_000
    doc_leg_axis = 2 if smoke else 8

    on_accelerator = jax.devices()[0].platform != "cpu"
    tmp = tempfile.mkdtemp(prefix="bench_mesh_")

    def engines(mesh, leg):
        labels_on = {"bench_leg": leg, "merge": "on"}
        eng_on = TpuOperatorExecutor(mesh=mesh, metrics_labels=labels_on)
        eng_off = TpuOperatorExecutor(
            mesh=mesh,
            config=PinotConfiguration(overrides={
                "pinot.server.mesh.collective.merge": False}),
            metrics_labels={"bench_leg": leg, "merge": "off"})
        return eng_on, eng_off, labels_on

    try:
        # -- leg 1: segments axis, weak scaling, fixed per-chip data --
        seg_points = []
        for n in counts:
            doc_axis = 2 if n % 2 == 0 else 1
            mesh = make_mesh(jax.devices()[:n], doc_axis=doc_axis)
            num_segments = segs_per_chip * n
            segs = _mesh_build_table(
                tmp, f"ssb_m{n}", num_segments, docs, seed=9000 + n)
            eng_on, eng_off, labels_on = engines(mesh, f"seg{n}")
            m = _mesh_measure(eng_on, eng_off, segs, f"ssb_m{n}",
                              num_segments * docs, rounds, window_s,
                              p50_iters, labels_on)
            m.update(devices=n, mesh={"segments": n // doc_axis,
                                      "docs": doc_axis},
                     segments=num_segments, docs_per_segment=docs)
            m["rows_per_sec_per_chip"] = round(m["rows_per_sec"] / n)
            seg_points.append(m)
        base_per_chip = seg_points[0]["rows_per_sec_per_chip"]
        for m in seg_points:
            m["efficiency"] = round(
                m["rows_per_sec_per_chip"] / max(base_per_chip, 1), 3)

        # -- leg 2: docs axis, ONE huge segment ------------------------
        big = _mesh_build_table(tmp, "ssb_big", 1, doc_leg_docs, seed=17)
        mesh_doc = make_mesh(jax.devices()[:doc_leg_axis],
                             doc_axis=doc_leg_axis)
        eng_on, eng_off, labels_on = engines(mesh_doc, "docleg")
        doc_leg = _mesh_measure(eng_on, eng_off, big, "ssb_big",
                                doc_leg_docs, rounds, window_s,
                                p50_iters, labels_on)
        mesh_one = make_mesh(jax.devices()[:1], doc_axis=1)
        eng1_on, eng1_off, labels1 = engines(mesh_one, "docleg1")
        doc_base = _mesh_measure(eng1_on, eng1_off, big, "ssb_big",
                                 doc_leg_docs, rounds, window_s,
                                 p50_iters, labels1)
        doc_leg.update(
            devices=doc_leg_axis,
            mesh={"segments": 1, "docs": doc_leg_axis},
            segments=1, docs_per_segment=doc_leg_docs,
            single_device_rows_per_sec=doc_base["rows_per_sec"],
            doc_shard_speedup=round(
                doc_leg["rows_per_sec"]
                / max(doc_base["rows_per_sec"], 1), 2))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    eff_floor = 0.8
    cpu_total_floor = 0.5
    out = {
        "metric": "mesh_weak_scaling_efficiency",
        "value": seg_points[-1]["efficiency"],
        "unit": "frac",
        "smoke": smoke,
        "platform": jax.devices()[0].platform,
        "segments_axis": seg_points,
        "doc_axis": doc_leg,
        "asserted": {
            "merged_rows_bit_identical_to_host_fold": True,
            "max_steady_retraces": 0,
            "min_efficiency_accelerator": eff_floor,
            "cpu_structural_floor":
                f"total rows/s at max mesh >= {cpu_total_floor}x the "
                f"1-device rate (shared-core stand-in: per-chip "
                f"efficiency is ~1/n there by construction)",
        },
    }
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_mesh.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))

    for m in seg_points + [doc_leg]:
        assert m["retraces_steady"] == 0, \
            f"steady-state retraces at {m.get('devices')}dev: " \
            f"{m['retraces_steady']}"
    for m in seg_points:
        if m["devices"] > 1:
            assert m["merge_served"] > 0, \
                f"merged path never served at {m['devices']}dev"
    if not smoke:
        if on_accelerator:
            for m in seg_points:
                assert m["efficiency"] >= eff_floor, \
                    f"weak-scaling efficiency {m['efficiency']} at " \
                    f"{m['devices']}dev under the {eff_floor} gate"
            assert doc_leg["doc_shard_speedup"] >= 2.0, \
                f"doc-axis leg speedup {doc_leg['doc_shard_speedup']}"
        else:
            top = seg_points[-1]
            assert top["rows_per_sec"] >= \
                cpu_total_floor * seg_points[0]["rows_per_sec"], \
                f"total throughput collapsed on the CPU stand-in: " \
                f"{top['rows_per_sec']} vs " \
                f"{seg_points[0]['rows_per_sec']} at 1 device"


def main():
    os.makedirs(DATA_DIR, exist_ok=True)
    build_data()
    segments = load()
    total_rows = sum(s.num_docs for s in segments)

    from pinot_tpu.query.executor import QueryExecutor

    tpu_ex = QueryExecutor(segments, use_tpu=True)
    seq_lat, tpu_resp = time_sequential(tpu_ex, n_iters=10)
    pipe_dt = time_pipelined(tpu_ex, PIPELINE_DEPTH, n_iters=64)

    cpu8_ex = QueryExecutor(segments, use_tpu=False, max_threads=8)
    cpu8_lat, cpu_resp = time_sequential(cpu8_ex, n_iters=2, warmup=1)
    cpu1_ex = QueryExecutor(segments, use_tpu=False, max_threads=1)
    cpu1_lat, cpu1_resp = time_sequential(cpu1_ex, n_iters=2, warmup=1)

    # sanity: int SUM and COUNT are BIT-EXACT on the device path (isum
    # plane accumulation, ops/kernels.py _isum_slot)
    t, c = tpu_resp.rows[0], cpu_resp.rows[0]
    assert c[1] == t[1], f"count mismatch: {t} vs {c}"
    assert float(t[0]) == float(c[0]), f"sum mismatch: {t} vs {c}"
    assert cpu1_resp.rows[0][1] == c[1]

    rows_per_sec = total_rows / pipe_dt
    seq_rows_per_sec = total_rows / statistics.median(seq_lat)
    cpu8_rps = total_rows / statistics.median(cpu8_lat)
    cpu1_rps = total_rows / statistics.median(cpu1_lat)
    # this bench host has few cores (often 1) — threads can't speed numpy
    # up there, so the honest host baseline is whichever config is fastest
    host_best = max(cpu1_rps, cpu8_rps)
    dev_dt, staged_bytes = measure_device_kernel(tpu_ex, segments)
    if staged_bytes is None:
        staged_bytes = total_rows * BYTES_PER_ROW
    eff_gbps = staged_bytes / 1e9 / pipe_dt
    dev_gbps = staged_bytes / 1e9 / dev_dt if dev_dt else 0.0
    out = {
        "metric": "ssb_q1_scan_agg_rows_per_sec_per_chip",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / host_best, 2),
        "host_cpu_cores": os.cpu_count(),
        "pipeline_depth": PIPELINE_DEPTH,
        "p50_query_ms": round(statistics.median(seq_lat) * 1e3, 1),
        "p90_query_ms": round(
            sorted(seq_lat)[max(0, -(-len(seq_lat) * 9 // 10) - 1)] * 1e3, 1),
        "pipelined_query_ms": round(pipe_dt * 1e3, 2),
        "sequential_rows_per_sec": round(seq_rows_per_sec),
        "link_rt_ms": round(measure_link_rt_ms(), 1),
        "effective_gbps": round(eff_gbps, 1),
        "roofline_frac_v5e": round(eff_gbps / 819.0, 3),
        # device-only steady-state kernel (no link/host costs): with
        # cardinality-aware i8/i16 id staging the kernel reads ~40% fewer
        # bytes and is now VPU-COMPUTE-bound (mask evaluation + exact-sum
        # planes), not HBM-bound — GB/s understates the win; rows/s is
        # the honest headline
        "device_time_ms": round(dev_dt * 1e3, 2) if dev_dt else None,
        "device_rows_per_sec": round(total_rows / dev_dt) if dev_dt else None,
        "device_gbps": round(dev_gbps, 1),
        "staged_bytes_per_row": round(staged_bytes / total_rows, 1),
        "host_rows_per_sec_8t": round(cpu8_rps),
        "host_rows_per_sec_1t": round(cpu1_rps),
        "vs_host_1t": round(rows_per_sec / cpu1_rps, 2),
    }
    out.update(phase_breakdown(tpu_ex.tpu_engine, segments))
    print(json.dumps(out))


# ---------------------------------------------------------------------------
# --vector: ANN top-K as a batched device matmul (ISSUE 20)
# ---------------------------------------------------------------------------

def vector_main(smoke: bool = False, out_path: str = None):
    """--vector [--smoke]: the vector-similarity device leg's acceptance
    driver (ISSUE 20).

    Compute A/B — the same K-nearest query answered two ways: the HOST
    path walks the segments serially (per-segment VectorIndex.top_k:
    a [n, d] matmul + full lexsort each) and merges; the DEVICE path is
    ONE batched einsum + jax.lax.top_k over the staged [S, docs, d]
    block with a trivial cross-segment merge. Speedup gates at 2x on the
    CPU stand-in and 5x on a real accelerator (full run only).

    Exact parity — on a table below the IVF threshold the device leg
    must return doc ids BIT-IDENTICAL to VectorIndex.top_k (both sides
    break score ties toward the lower doc id by construction).

    Recall — on the IVF table, device answers (nprobe-pruned via the
    staged cell mask) score recall@K against the exact ground truth
    computed from the same stored vectors; gate 0.9.

    Coalesce — 8 clients loop fingerprint-equal ANN queries (same
    col/K/plan, DIFFERENT query vectors — the vectors ride params, not
    the plan) against one pipelined engine: they must batch into shared
    jit(vmap) launches (batch max > 1) with ZERO steady-state retraces.

    Writes BENCH_vector.json. --smoke shrinks sizes to tier-1 budget."""
    import contextlib
    import statistics as stats
    import tempfile
    import threading

    import jax

    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig)
    from pinot_tpu.ops import dispatch as dispatch_mod
    from pinot_tpu.ops import kernels, vector_device
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.utils.config import PinotConfiguration

    docs_per_seg = 4200 if smoke else 8192   # >= IVF_THRESHOLD: coarse layer
    num_segments = 2 if smoke else 4
    d, k = 16, 10
    p50_iters = 5 if smoke else 25
    dev_iters = 8 if smoke else 25
    recall_queries = 8 if smoke else 50
    window_s = 0.8 if smoke else 2.5
    clients = 8

    tmp = tempfile.mkdtemp(prefix="bench_vector_")

    # clustered embeddings (a Gaussian mixture), not white noise: IVF
    # recall on uniform-random data is meaningless — in d=16 the true
    # neighbor set of a random point scatters across every cell. Real
    # embedding spaces cluster, which is exactly what the coarse layer
    # exploits; queries perturb stored vectors (the lookup workload).
    centers = np.random.default_rng(5999).normal(size=(32, d)) * 2.0

    def build_table(name, n_per_seg, nseg, seed):
        schema = Schema(name, [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("vec", DataType.STRING, FieldType.DIMENSION)])
        tc = TableConfig(name=name)
        tc.indexing.vector_index_columns = ["vec"]
        creator = SegmentCreator(tc, schema)
        segs = []
        for i in range(nseg):
            rng = np.random.default_rng(seed + i)
            which = rng.integers(0, len(centers), n_per_seg)
            vecs = (centers[which]
                    + 0.3 * rng.normal(size=(n_per_seg, d))
                    ).astype(np.float32)
            out = os.path.join(tmp, f"{name}_{i}")
            creator.build({
                "id": np.arange(n_per_seg) + i * n_per_seg,
                "vec": np.array([json.dumps([float(x) for x in row])
                                 for row in vecs], object),
            }, out, f"{name}_{i}")
            segs.append(load_segment(out))
        return segs

    segs = build_table("emb", docs_per_seg, num_segments, 6000)
    segs_exact = build_table("embx", 1000, 1, 6100)
    indexes = [vector_device._index_of(s, "vec") for s in segs]
    assert all(ix is not None and ix.centroids is not None
               for ix in indexes), "IVF layer did not engage"

    labels = {"bench_leg": "vector"}
    eng = TpuOperatorExecutor(config=PinotConfiguration(),
                              metrics_labels=labels)
    reg = eng._dispatcher._metrics
    ex_dev = QueryExecutor(segs, use_tpu=True, engine=eng)
    ex_host = QueryExecutor(segs, use_tpu=False)

    rng = np.random.default_rng(9)

    def data_query():
        # perturb a stored (already-normalized) vector — the ANN lookup
        # workload: the query lives in the indexed embedding space
        ix = indexes[int(rng.integers(0, num_segments))]
        base = ix.vectors[int(rng.integers(0, len(ix.vectors)))]
        return (base + 0.05 * rng.normal(size=d)).astype(np.float32)

    def qsql(qv, table="emb", kk=k, lim=None):
        lit = json.dumps([float(x) for x in qv])
        sql = (f"SELECT id FROM {table} "
               f"WHERE vector_similarity(vec, '{lit}', {kk})")
        return sql if lim is None else f"{sql} LIMIT {lim}"

    # -- exact parity: device ids bit-identical to VectorIndex.top_k --
    ex_exact = QueryExecutor(segs_exact, use_tpu=True, engine=eng)
    ix_exact = vector_device._index_of(segs_exact[0], "vec")
    assert ix_exact.centroids is None  # exact path
    for _ in range(5):
        qv = rng.normal(size=d).astype(np.float32)
        r = ex_exact.execute(qsql(qv, table="embx"))
        assert not r.exceptions, r.exceptions
        got = sorted(row[0] for row in r.rows)
        want = sorted(int(i) for i in ix_exact.top_k(qv, k))
        assert got == want, (got, want)

    # -- IVF recall@k vs exact ground truth over the stored vectors.
    # vector_similarity is a per-segment FILTER (K matches per segment,
    # host contract) — ground truth is the union of per-segment exact
    # top-k, and the query's LIMIT spans the whole union.
    def exact_union(qv, kk):
        qn = (qv / max(np.linalg.norm(qv), 1e-30)).astype(np.float32)
        docs = set()
        for si, ix in enumerate(indexes):
            sc = ix.vectors @ qn
            order = np.lexsort((np.arange(len(sc)), -sc))
            docs |= {si * docs_per_seg + int(t) for t in order[:kk]}
        return docs

    recalls = []
    for _ in range(recall_queries):
        qv = data_query()
        r = ex_dev.execute(qsql(qv, lim=k * num_segments))
        assert not r.exceptions, r.exceptions
        got = {row[0] for row in r.rows}
        truth = exact_union(qv, k)
        recalls.append(len(got & truth) / len(truth))
    recall = float(np.mean(recalls))

    # -- compute A/B: serialized host walk vs one batched launch ------
    qv0 = data_query()
    prep = eng._prepare_vector(segs, QueryContext.from_sql(qsql(qv0)),
                               None)
    assert prep is not None, "device leg refused the bench query"
    launch = prep[2]
    guard = dispatch_mod._CPU_COLLECTIVE_LOCK if launch.collective \
        else contextlib.nullcontext()
    with guard:
        jax.block_until_ready(launch.call())  # warm
        t0 = time.perf_counter()
        for _ in range(dev_iters):
            jax.block_until_ready(launch.call())
        device_ms = (time.perf_counter() - t0) / dev_iters * 1e3

    def host_walk():
        cand = []
        for si, ix in enumerate(indexes):
            for t in ix.top_k(qv0, k):
                cand.append(si * docs_per_seg + int(t))
        return cand

    host_walk()  # warm any lazy state
    t0 = time.perf_counter()
    for _ in range(dev_iters):
        host_walk()
    host_ms = (time.perf_counter() - t0) / dev_iters * 1e3
    speedup = host_ms / max(device_ms, 1e-9)

    def p50(ex, sql):
        lat = []
        for _ in range(p50_iters):
            t0 = time.perf_counter()
            ex.execute(sql)
            lat.append((time.perf_counter() - t0) * 1e3)
        return stats.median(lat)

    p50_dev = p50(ex_dev, qsql(qv0))
    p50_host = p50(ex_host, qsql(qv0))

    # -- coalesce: 8 clients, same plan, different query vectors ------
    coal_q = [data_query() for _ in range(clients)]
    for qv in coal_q:          # params-cache every query vector
        ex_dev.execute(qsql(qv))
    b = 2
    while b <= dispatch_mod._pow2(clients):   # warm the batch buckets
        kern = launch.factory(b, False)
        with guard:
            jax.block_until_ready(kern(
                launch.cols, (launch.params,) * b, launch.num_docs,
                D=launch.D, G=launch.G))
        b *= 2
    traces0 = kernels.trace_count()
    batch_t0 = reg.timer("dispatch_batch_size", labels=labels)
    count0, max0 = batch_t0.count, batch_t0.max_ms
    stop_at = time.perf_counter() + window_s
    done = [0] * clients

    def client(ci):
        j = 0
        while time.perf_counter() < stop_at:
            ex_dev.execute(qsql(coal_q[(ci + j) % clients]))
            done[ci] += 1
            j += 1

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    retraces = kernels.trace_count() - traces0
    batch_t = reg.timer("dispatch_batch_size", labels=labels)
    platform = jax.devices()[0].platform
    gate = 2.0 if platform == "cpu" else 5.0
    out = {
        "metric": "vector_device_vs_host_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "smoke": smoke,
        "platform": platform,
        "docs": docs_per_seg * num_segments,
        "dim": d, "k": k,
        "device_ms": round(device_ms, 3),
        "host_walk_ms": round(host_ms, 3),
        "p50_device_ms": round(p50_dev, 2),
        "p50_host_ms": round(p50_host, 2),
        "recall_at_k": round(recall, 3),
        "vector_served": int(reg.meter("vector_served", labels=labels)),
        "coalesce": {
            "clients": clients,
            "queries_completed": int(sum(done)),
            "qps": round(sum(done) / wall, 2),
            "batch_launches": batch_t.count - count0,
            "batch_size_max": max(batch_t.max_ms, max0),
            "retraces_steady": retraces,
        },
        "asserted": {
            "exact_parity": "device doc ids == VectorIndex.top_k",
            "min_recall_at_k": 0.9,
            "max_steady_retraces": 0,
            "min_batch_size": 2,
            "full_run_only": f"device >= {gate}x host "
                             f"({platform} gate)",
        },
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_vector.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    assert recall >= 0.9, f"IVF recall@{k} = {recall:.3f} < 0.9"
    assert retraces == 0, f"steady-state retraces: {retraces}"
    assert out["coalesce"]["batch_size_max"] >= 2, \
        "fingerprint-equal ANN queries never coalesced"
    if not smoke:
        assert speedup >= gate, \
            f"device {speedup:.2f}x host, below the {gate}x {platform} gate"


# ---------------------------------------------------------------------------
# --timeseries: dashboards as device group-bys (ISSUE 20)
# ---------------------------------------------------------------------------

def timeseries_main(smoke: bool = False, out_path: str = None):
    """--timeseries [--smoke]: the device time-bucket leg's acceptance
    driver (ISSUE 20).

    A/B — the same simpleql dashboard query served (a) through the
    device group-by kernel with floor((t-start)/step) FUSED into the
    group key (pinot.server.timeseries.bucket.enabled=true) and (b) by
    the host expression-column leaf (the pre-ISSUE-20 path, which the
    device scan leg can't admit). Full run asserts the fused leg wins
    end-to-end. A sliding-refresh loop (start advances every query, the
    dashboard steady state) must cause ZERO retraces: start/step/count
    ride params, only count_pad is in the plan.

    Selfmetrics — the PR-14 dogfood dashboards run end-to-end through
    the device leg (query_history(use_tpu=True)), making metrics
    history a third device workload beside queries and log search.

    Writes BENCH_timeseries.json. --smoke shrinks to tier-1 budget."""
    import statistics as stats
    import tempfile

    import jax

    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig)
    from pinot_tpu.ops import kernels
    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.timeseries.engine import query as ts_query
    from pinot_tpu.utils.config import PinotConfiguration

    docs_per_seg = 10_000 if smoke else 100_000
    num_segments = 2 if smoke else 4
    n_tags = 8
    # a 30-point dashboard panel: 32-pad buckets x 8 tags = 256 padded
    # groups — inside the kernel's one-hot/MXU scatter regime on both
    # backends (the one-hot cost is linear in padded groups, which is
    # what the CPU stand-in pays; accelerators eat it on the MXU)
    buckets = 30
    step = 20
    t0_, t1 = 100_000, 100_000 + buckets * step
    p50_iters = 5 if smoke else 20
    slide_iters = 6 if smoke else 20

    tmp = tempfile.mkdtemp(prefix="bench_ts_")
    schema = Schema("metrics", [
        FieldSpec("ts", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("host", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("value", DataType.DOUBLE, FieldType.METRIC)])
    creator = SegmentCreator(TableConfig(name="metrics"), schema)
    segs = []
    for i in range(num_segments):
        rng = np.random.default_rng(7000 + i)
        out_dir = os.path.join(tmp, f"m_{i}")
        creator.build({
            "ts": rng.integers(t0_, t1, docs_per_seg),
            "host": np.array([f"h{v}" for v in
                              rng.integers(0, n_tags, docs_per_seg)],
                             object),
            "value": rng.normal(size=docs_per_seg),
        }, out_dir, f"m_{i}")
        segs.append(load_segment(out_dir))

    labels = {"bench_leg": "ts"}
    eng_dev = TpuOperatorExecutor(config=PinotConfiguration(),
                                  metrics_labels=labels)
    eng_off = TpuOperatorExecutor(
        config=PinotConfiguration(overrides={
            "pinot.server.timeseries.bucket.enabled": False}),
        metrics_labels={"bench_leg": "ts_off"})
    reg = eng_dev._dispatcher._metrics
    ex_dev = QueryExecutor(segs, use_tpu=True, engine=eng_dev)
    ex_off = QueryExecutor(segs, use_tpu=True, engine=eng_off)

    def dash(start):
        return (f"fetch(metrics, value, ts, {start}, {t1}, {step}) "
                f"| groupby(host) | sum(host) | keep_last_value()")

    # -- parity: fused bucket leg == expression-column leaf -----------
    served0 = reg.meter("timeseries_leaf_device", labels=labels)
    bd = ts_query(dash(t0_), ex_dev)
    bh = ts_query(dash(t0_), ex_off)
    assert reg.meter("timeseries_leaf_device", labels=labels) > served0, \
        "bucket group-by did not serve through the device leg"
    dd = {s.tag_key(): s.values for s in bd.series}
    hh = {s.tag_key(): s.values for s in bh.series}
    assert set(dd) == set(hh), "series sets diverge"
    for key in dd:
        # f32 device sums of SIGNED values: cancellation makes relative
        # error meaningless near zero, hence the atol floor
        np.testing.assert_allclose(
            dd[key], hh[key], rtol=1e-3, atol=1e-3, equal_nan=True)

    # -- sliding refresh: params move, the kernel must not retrace ----
    traces0 = kernels.trace_count()
    for j in range(slide_iters):
        ts_query(dash(t0_ + (j % 4) * step), ex_dev)
    slide_retraces = kernels.trace_count() - traces0

    def p50(ex):
        lat = []
        for _ in range(p50_iters):
            t0 = time.perf_counter()
            ts_query(dash(t0_), ex)
            lat.append((time.perf_counter() - t0) * 1e3)
        return stats.median(lat)

    p50_dev = p50(ex_dev)
    p50_off = p50(ex_off)

    # -- selfmetrics dashboards through the device leg ----------------
    from pinot_tpu.health.history import MetricsHistory, MetricsSampler
    from pinot_tpu.health.selfmetrics import query_history
    from pinot_tpu.utils.metrics import MetricsRegistry
    role = "bench-ts"
    sreg = MetricsRegistry(role)
    hist = MetricsHistory(64)
    sampler = MetricsSampler(role, history=hist, registry=sreg)
    base = 1_000_000
    for i in range(20):
        sreg.add_meter("queries", 3)
        s = sampler.sample_once()
        s["ts"] = base + i
    served0 = reg.meter("timeseries_leaf_device", labels=labels)
    block = query_history(
        f"fetch(selfmetrics, value, ts, {base}, {base + 20}, 1) "
        f"| where(family = 'queries') | sum() | rate()",
        role=role, history=hist, use_tpu=True, engine=eng_dev)
    assert block.series and np.allclose(block.series[0].values[1:], 3.0)
    selfm_device = reg.meter("timeseries_leaf_device",
                             labels=labels) > served0

    platform = jax.devices()[0].platform
    leaf_gate = 1.1 if platform == "cpu" else 2.0
    out = {
        "metric": "timeseries_device_vs_expression_leaf_p50",
        "value": round(p50_off / max(p50_dev, 1e-9), 2),
        "unit": "x",
        "smoke": smoke,
        "platform": platform,
        "docs": docs_per_seg * num_segments,
        "buckets": buckets, "tags": n_tags,
        "p50_device_ms": round(p50_dev, 2),
        "p50_expression_leaf_ms": round(p50_off, 2),
        "slide_retraces": slide_retraces,
        "selfmetrics_device": bool(selfm_device),
        "timeseries_leaf_device": int(
            reg.meter("timeseries_leaf_device", labels=labels)),
        "asserted": {
            "parity": "fused bucket leg == expression leaf "
                      "(1e-3 rel, 1e-3 abs — f32 signed sums)",
            "max_slide_retraces": 0,
            "selfmetrics_device": True,
            "full_run_only": f"device >= {leaf_gate}x expression leaf "
                             f"({platform} gate)",
        },
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_timeseries.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    assert slide_retraces == 0, \
        f"sliding refresh retraced {slide_retraces}x"
    assert selfm_device, \
        "selfmetrics dashboard bypassed the device bucket leg"
    if not smoke:
        ratio = p50_off / max(p50_dev, 1e-9)
        assert ratio >= leaf_gate, \
            f"device {p50_dev:.2f}ms only {ratio:.2f}x the expression " \
            f"leaf ({p50_off:.2f}ms), below the {leaf_gate}x " \
            f"{platform} gate"


if __name__ == "__main__":
    if "--deadline-overhead" in sys.argv:
        deadline_overhead_main()
    elif "--trace-overhead" in sys.argv:
        trace_overhead_main(smoke="--smoke" in sys.argv)
    elif "--concurrency" in sys.argv:
        concurrency_main(smoke="--smoke" in sys.argv)
    elif "--residency" in sys.argv:
        residency_main(smoke="--smoke" in sys.argv)
    elif "--mse" in sys.argv:
        mse_main(smoke="--smoke" in sys.argv)
    elif "--groups" in sys.argv:
        groups_main(smoke="--smoke" in sys.argv)
    elif "--batching" in sys.argv:
        batching_main(smoke="--smoke" in sys.argv)
    elif "--startree" in sys.argv:
        startree_main(smoke="--smoke" in sys.argv)
    elif "--ingest" in sys.argv:
        ingest_main(smoke="--smoke" in sys.argv)
    elif "--health" in sys.argv:
        health_main(smoke="--smoke" in sys.argv)
    elif "--overload" in sys.argv:
        overload_main(smoke="--smoke" in sys.argv)
    elif "--logs" in sys.argv:
        logs_main(smoke="--smoke" in sys.argv)
    elif "--rebalance" in sys.argv:
        rebalance_main(smoke="--smoke" in sys.argv)
    elif "--mesh" in sys.argv:
        mesh_main(smoke="--smoke" in sys.argv)
    elif "--vector" in sys.argv:
        vector_main(smoke="--smoke" in sys.argv)
    elif "--timeseries" in sys.argv:
        timeseries_main(smoke="--smoke" in sys.argv)
    else:
        main()
