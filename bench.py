"""Benchmark: SSB Q1.1-shaped scan-aggregation on the TPU query engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config #2 from BASELINE.md: flat-lineorder range-filter + SUM, no index.
  SELECT SUM(lo_extendedprice * lo_discount) FROM ssb
  WHERE lo_orderdate BETWEEN 19940101 AND 19940131
    AND lo_discount BETWEEN 4 AND 6 AND lo_quantity BETWEEN 26 AND 35
value = device rows-scanned/sec (one chip); vs_baseline = speedup over the
single-process numpy reference executor on the same segments (the stand-in
for the JVM single-node reference until a JVM run is recorded).

Segments are built once into ./bench_data (git-ignored) and reloaded on
later runs; columns stay HBM-resident across queries (the segment cache of
SURVEY.md §7.5), so steady-state timing reflects the scan path, not I/O.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_SEGMENTS = 16
DOCS_PER_SEGMENT = 8_000_000
DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_data")
QUERY = ("SELECT SUM(lo_extendedprice * lo_discount), COUNT(*) FROM ssb "
         "WHERE lo_orderdate BETWEEN 19940101 AND 19940131 "
         "AND lo_discount BETWEEN 4 AND 6 AND lo_quantity BETWEEN 26 AND 35")


def build_data():
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator

    schema = Schema("ssb", [
        FieldSpec("lo_orderdate", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_discount", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_quantity", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lo_extendedprice", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("ssb", TableType.OFFLINE)
    # high-cardinality measure stays raw (no dictionary); random ints are
    # incompressible, so skip chunk compression for build/load speed
    tc.indexing.no_dictionary_columns = ["lo_extendedprice"]
    tc.indexing.compression = "PASS_THROUGH"
    creator = SegmentCreator(tc, schema)
    dates = np.array([y * 10000 + m * 100 + d
                      for y in range(1992, 1999)
                      for m in range(1, 13) for d in range(1, 29)],
                     dtype=np.int32)
    for i in range(NUM_SEGMENTS):
        out = os.path.join(DATA_DIR, f"seg_{i}")
        if os.path.exists(os.path.join(out, "metadata.json")):
            continue
        rng = np.random.default_rng(1000 + i)
        n = DOCS_PER_SEGMENT
        cols = {
            "lo_orderdate": dates[rng.integers(0, len(dates), n)],
            "lo_discount": rng.integers(0, 11, n).astype(np.int32),
            "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
            "lo_extendedprice": rng.integers(90_000, 10_000_000, n).astype(np.int32),
        }
        creator.build(cols, out, f"ssb_{i}")


def load():
    from pinot_tpu.segment.loader import load_segment
    return [load_segment(os.path.join(DATA_DIR, f"seg_{i}"))
            for i in range(NUM_SEGMENTS)]


def time_executor(ex, n_iters: int, warmup: int = 2):
    for _ in range(warmup):
        resp = ex.execute(QUERY)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        resp = ex.execute(QUERY)
    dt = (time.perf_counter() - t0) / n_iters
    return dt, resp


def main():
    os.makedirs(DATA_DIR, exist_ok=True)
    build_data()
    segments = load()
    total_rows = sum(s.num_docs for s in segments)

    from pinot_tpu.query.executor import QueryExecutor

    tpu_ex = QueryExecutor(segments, use_tpu=True)
    tpu_dt, tpu_resp = time_executor(tpu_ex, n_iters=10)

    cpu_ex = QueryExecutor(segments, use_tpu=False, max_threads=1)
    cpu_dt, cpu_resp = time_executor(cpu_ex, n_iters=2, warmup=1)

    # sanity: answers must agree (f32 device accumulate tolerance)
    t, c = tpu_resp.rows[0], cpu_resp.rows[0]
    assert c[1] == t[1], f"count mismatch: {t} vs {c}"
    assert abs(t[0] - c[0]) <= 2e-3 * abs(c[0]), f"sum mismatch: {t} vs {c}"

    rows_per_sec = total_rows / tpu_dt
    cpu_rows_per_sec = total_rows / cpu_dt
    print(json.dumps({
        "metric": "ssb_q1_scan_agg_rows_per_sec_per_chip",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / cpu_rows_per_sec, 2),
    }))


if __name__ == "__main__":
    main()
