"""Benchmark: two-tier result cache — cold vs warm latency + hit ratio.

Prints ONE JSON line like bench.py: cold/warm p50 for a repeated
dashboard-style group-by over immutable segments, per-tier hit ratios,
and a freshness check (a realtime append must change the answer on the
very next query — the mutable tail never serves from cache).

`--remote` measures the distributed fabric instead: an in-process
cache-server role (cache/remote.py) mounted as L2 under a TieredCache,
reporting cold vs L1-warm vs L2-warm p50 (L2-warm = a fresh replica with
an empty L1 pulling a sibling's partials over the wire) plus the raw
remote round-trip overhead, and writing BENCH_cache_remote.json next to
this file.

Runnable anywhere: `JAX_PLATFORMS=cpu python bench_cache.py` uses the
host executor; on a TPU host the device engine path is exercised too.
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_SEGMENTS = 8
DOCS_PER_SEGMENT = 200_000
ITERS = 30
DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_cache_data")
QUERY = ("SELECT d, COUNT(*), SUM(m) FROM t WHERE d < 48 "
         "GROUP BY d ORDER BY d LIMIT 50")


def build_segments():
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    schema = Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "m", "dataType": "LONG"}]})
    tc = TableConfig.from_dict({"tableName": "t", "tableType": "OFFLINE"})
    creator = SegmentCreator(tc, schema)
    segs = []
    rng = np.random.default_rng(7)
    for i in range(NUM_SEGMENTS):
        seg_dir = os.path.join(DATA_DIR, f"seg_{i}")
        if not os.path.isdir(seg_dir):
            creator.build(
                {"d": rng.integers(0, 64, DOCS_PER_SEGMENT).astype(np.int64),
                 "m": rng.integers(0, 1000,
                                   DOCS_PER_SEGMENT).astype(np.int64)},
                seg_dir, f"bench_{i}")
        segs.append(load_segment(seg_dir))
    return schema, tc, segs


def p50(xs):
    return statistics.median(xs) * 1000.0


def main_remote() -> None:
    """Fabric mode: cold vs L1-warm vs L2-warm p50 + remote RTT."""
    from pinot_tpu.cache import (CacheServer, LruTtlCache,
                                 RemoteCacheBackend, SegmentResultCache,
                                 TieredCache)
    from pinot_tpu.cache.segment_cache import segment_remote_key
    from pinot_tpu.query.executor import QueryExecutor

    import jax
    use_tpu = jax.devices()[0].platform != "cpu"
    _, _, segs = build_segments()
    server = CacheServer(max_bytes=512 << 20, ttl_seconds=600.0)
    server.start()

    def tiered_cache():
        """A fresh replica: empty L1 over the SHARED warm L2."""
        return SegmentResultCache(backend=TieredCache(
            LruTtlCache(256 << 20, 600.0),
            RemoteCacheBackend(server.address), segment_remote_key))

    def run(cache):
        t0 = time.perf_counter()
        r = QueryExecutor(segs, use_tpu=use_tpu,
                          segment_cache=cache).execute(QUERY)
        return time.perf_counter() - t0, r

    try:
        # cold: both tiers empty every iteration
        cold = []
        for _ in range(ITERS):
            server.cache.clear()
            replica = tiered_cache()
            dt, cold_resp = run(replica)
            cold.append(dt)
            replica._cache.close()
        baseline_rows = cold_resp.result_table.rows

        # L1-warm: one replica, primed, repeated dashboard refresh
        server.cache.clear()
        primed = tiered_cache()
        run(primed)
        l1_warm = []
        for _ in range(ITERS):
            dt, r = run(primed)
            l1_warm.append(dt)
        assert r.result_table.rows == baseline_rows, "L1 corrupted rows"

        # L2-warm: a NEW replica each iteration — empty L1, warm shared
        # tier — i.e. the rollout/cold-replica path the fabric exists for
        l2_warm = []
        for _ in range(ITERS):
            replica = tiered_cache()
            dt, r = run(replica)
            l2_warm.append(dt)
            assert replica._cache.l2.hits >= len(segs), "L2 did not serve"
            replica._cache.close()
        assert r.result_table.rows == baseline_rows, "L2 corrupted rows"

        # raw remote round trip: GET of one representative entry
        be = RemoteCacheBackend(server.address)
        probe_key = next(iter(server.cache._entries))
        rtts = []
        for _ in range(200):
            t0 = time.perf_counter()
            be.get(probe_key)
            rtts.append(time.perf_counter() - t0)
        be.close()
        primed._cache.close()
    finally:
        server.stop()

    cold_p50, l1_p50, l2_p50 = p50(cold), p50(l1_warm), p50(l2_warm)
    out = {
        "metric": "remote_cache_l2_warm_speedup",
        "value": round(cold_p50 / l2_p50, 2) if l2_p50 else None,
        "unit": "x",
        "cold_p50_ms": round(cold_p50, 3),
        "l1_warm_p50_ms": round(l1_p50, 3),
        "l2_warm_p50_ms": round(l2_p50, 3),
        "remote_rtt_p50_ms": round(p50(rtts), 3),
        "l2_over_l1_overhead_ms": round(l2_p50 - l1_p50, 3),
        "num_segments": NUM_SEGMENTS,
        "docs_per_segment": DOCS_PER_SEGMENT,
        "use_tpu": use_tpu,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_cache_remote.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


def main() -> None:
    from pinot_tpu.cache import SegmentResultCache
    from pinot_tpu.ingest.mutable_segment import MutableSegment
    from pinot_tpu.models.table_config import TableType
    from pinot_tpu.query.executor import QueryExecutor

    import jax
    use_tpu = jax.devices()[0].platform != "cpu"
    schema, tc, segs = build_segments()
    cache = SegmentResultCache()

    def run():
        t0 = time.perf_counter()
        r = QueryExecutor(segs, use_tpu=use_tpu,
                          segment_cache=cache).execute(QUERY)
        return time.perf_counter() - t0, r

    # cold: every iteration re-executes all segments
    cold = []
    for _ in range(ITERS):
        cache.clear()
        dt, cold_resp = run()
        cold.append(dt)
    baseline_rows = cold_resp.result_table.rows

    # warm: primed cache, repeated dashboard refresh
    cache.clear()
    run()  # prime
    warm = []
    for _ in range(ITERS):
        dt, warm_resp = run()
        warm.append(dt)
    assert warm_resp.result_table.rows == baseline_rows, "cache corrupted rows"
    hit_ratio = cache.stats.hit_ratio

    # freshness: append one row to a consuming segment — the next query
    # MUST see it (mutable tail never cached); immutable bulk still hits
    rt_tc = tc
    rt_tc.table_type = TableType.REALTIME
    mut = MutableSegment("t__0__0__0", rt_tc, schema)
    mut.index({"d": 1, "m": 1})
    hybrid = list(segs) + [mut]
    count_sql = "SELECT COUNT(*) FROM t"
    n1 = QueryExecutor(hybrid, use_tpu=use_tpu,
                       segment_cache=cache).execute(count_sql).rows[0][0]
    mut.index({"d": 2, "m": 1})
    n2 = QueryExecutor(hybrid, use_tpu=use_tpu,
                       segment_cache=cache).execute(count_sql).rows[0][0]
    fresh = (n2 == n1 + 1)

    cold_p50, warm_p50 = p50(cold), p50(warm)
    print(json.dumps({
        "metric": "segment_cache_warm_speedup",
        "value": round(cold_p50 / warm_p50, 2) if warm_p50 else None,
        "unit": "x",
        "cold_p50_ms": round(cold_p50, 3),
        "warm_p50_ms": round(warm_p50, 3),
        "hit_ratio": round(hit_ratio, 4),
        "fresh_after_append": fresh,
        "num_segments": NUM_SEGMENTS,
        "docs_per_segment": DOCS_PER_SEGMENT,
        "use_tpu": use_tpu,
    }))
    if not fresh:
        sys.exit(1)


if __name__ == "__main__":
    if "--remote" in sys.argv[1:]:
        main_remote()
    else:
        main()
