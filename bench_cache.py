"""Benchmark: two-tier result cache — cold vs warm latency + hit ratio.

Prints ONE JSON line like bench.py: cold/warm p50 for a repeated
dashboard-style group-by over immutable segments, per-tier hit ratios,
and a freshness check (a realtime append must change the answer on the
very next query — the mutable tail never serves from cache).

Runnable anywhere: `JAX_PLATFORMS=cpu python bench_cache.py` uses the
host executor; on a TPU host the device engine path is exercised too.
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_SEGMENTS = 8
DOCS_PER_SEGMENT = 200_000
ITERS = 30
DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_cache_data")
QUERY = ("SELECT d, COUNT(*), SUM(m) FROM t WHERE d < 48 "
         "GROUP BY d ORDER BY d LIMIT 50")


def build_segments():
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    schema = Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "m", "dataType": "LONG"}]})
    tc = TableConfig.from_dict({"tableName": "t", "tableType": "OFFLINE"})
    creator = SegmentCreator(tc, schema)
    segs = []
    rng = np.random.default_rng(7)
    for i in range(NUM_SEGMENTS):
        seg_dir = os.path.join(DATA_DIR, f"seg_{i}")
        if not os.path.isdir(seg_dir):
            creator.build(
                {"d": rng.integers(0, 64, DOCS_PER_SEGMENT).astype(np.int64),
                 "m": rng.integers(0, 1000,
                                   DOCS_PER_SEGMENT).astype(np.int64)},
                seg_dir, f"bench_{i}")
        segs.append(load_segment(seg_dir))
    return schema, tc, segs


def p50(xs):
    return statistics.median(xs) * 1000.0


def main() -> None:
    from pinot_tpu.cache import SegmentResultCache
    from pinot_tpu.ingest.mutable_segment import MutableSegment
    from pinot_tpu.models.table_config import TableType
    from pinot_tpu.query.executor import QueryExecutor

    import jax
    use_tpu = jax.devices()[0].platform != "cpu"
    schema, tc, segs = build_segments()
    cache = SegmentResultCache()

    def run():
        t0 = time.perf_counter()
        r = QueryExecutor(segs, use_tpu=use_tpu,
                          segment_cache=cache).execute(QUERY)
        return time.perf_counter() - t0, r

    # cold: every iteration re-executes all segments
    cold = []
    for _ in range(ITERS):
        cache.clear()
        dt, cold_resp = run()
        cold.append(dt)
    baseline_rows = cold_resp.result_table.rows

    # warm: primed cache, repeated dashboard refresh
    cache.clear()
    run()  # prime
    warm = []
    for _ in range(ITERS):
        dt, warm_resp = run()
        warm.append(dt)
    assert warm_resp.result_table.rows == baseline_rows, "cache corrupted rows"
    hit_ratio = cache.stats.hit_ratio

    # freshness: append one row to a consuming segment — the next query
    # MUST see it (mutable tail never cached); immutable bulk still hits
    rt_tc = tc
    rt_tc.table_type = TableType.REALTIME
    mut = MutableSegment("t__0__0__0", rt_tc, schema)
    mut.index({"d": 1, "m": 1})
    hybrid = list(segs) + [mut]
    count_sql = "SELECT COUNT(*) FROM t"
    n1 = QueryExecutor(hybrid, use_tpu=use_tpu,
                       segment_cache=cache).execute(count_sql).rows[0][0]
    mut.index({"d": 2, "m": 1})
    n2 = QueryExecutor(hybrid, use_tpu=use_tpu,
                       segment_cache=cache).execute(count_sql).rows[0][0]
    fresh = (n2 == n1 + 1)

    cold_p50, warm_p50 = p50(cold), p50(warm)
    print(json.dumps({
        "metric": "segment_cache_warm_speedup",
        "value": round(cold_p50 / warm_p50, 2) if warm_p50 else None,
        "unit": "x",
        "cold_p50_ms": round(cold_p50, 3),
        "warm_p50_ms": round(warm_p50, 3),
        "hit_ratio": round(hit_ratio, 4),
        "fresh_after_append": fresh,
        "num_segments": NUM_SEGMENTS,
        "docs_per_segment": DOCS_PER_SEGMENT,
        "use_tpu": use_tpu,
    }))
    if not fresh:
        sys.exit(1)


if __name__ == "__main__":
    main()
