"""All five BASELINE.md benchmark configs, host vs device, one JSON file.

Writes BENCH_extra.json:
  1 baseball_sum        — baseballStats-shaped full-scan SELECT SUM(runs)
                          (schema from the reference's
                          examples/batch/baseballStats/baseballStats_schema
                          .json; raw CSV is quickstart-downloaded and not
                          in-tree, so rows are synthesized to shape)
  2 ssb_q1              — range-filter + SUM (same data/query as bench.py)
  3 ssb_groupby         — SSB Q2.x-shaped GROUP BY over low-card dims
  4 distinct_percentile — NYC-taxi-shaped DISTINCTCOUNTHLL + PERCENTILE
                          TDIGEST on a high-cardinality column (device
                          sketch path: HLL register max-scatter over hashed
                          split planes + histogram partials for the digest)
  5 startree            — pre-aggregated SSB group-by via the star-tree
                          path vs the same query full-scan

Each entry: rows, device p50 ms + rows/s (pipelined where the engine
overlaps round trips), host-numpy p50 ms + rows/s, speedup. Segments
build once under ./bench_data_extra (git-ignored).
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "bench_data_extra")
PIPELINE_DEPTH = 8


def _build(name, schema_fields, cols_fn, num_segments, docs_per_segment,
           no_dict=(), star_tree=None):
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    schema = Schema(name, [FieldSpec(n, getattr(DataType, t),
                                     FieldType.METRIC if m
                                     else FieldType.DIMENSION)
                           for n, t, m in schema_fields])
    tc = TableConfig(name, TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = list(no_dict)
    tc.indexing.compression = "PASS_THROUGH"
    if star_tree is not None:
        tc.indexing.star_tree_configs = [star_tree]
    creator = SegmentCreator(tc, schema)
    segs = []
    for i in range(num_segments):
        out = os.path.join(DATA, f"{name}_{i}")
        if not os.path.exists(os.path.join(out, "metadata.json")):
            rng = np.random.default_rng(7000 + i)
            creator.build(cols_fn(rng, docs_per_segment), out, f"{name}_{i}")
        segs.append(load_segment(out))
    return segs


def _measure(segments, sql, check=None, pipeline=True, iters=6):
    from pinot_tpu.query.executor import QueryExecutor
    total = sum(s.num_docs for s in segments)

    tpu = QueryExecutor(segments, use_tpu=True)
    resp = tpu.execute(sql)  # warmup: stage + compile
    assert not resp.exceptions, resp.exceptions
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        resp = tpu.execute(sql)
        lat.append(time.perf_counter() - t0)
    dev_p50 = statistics.median(lat)
    dev_rps = total / dev_p50
    if pipeline:
        with ThreadPoolExecutor(PIPELINE_DEPTH) as pool:
            list(pool.map(lambda _: tpu.execute(sql), range(PIPELINE_DEPTH)))
            n = PIPELINE_DEPTH * 4
            t0 = time.perf_counter()
            list(pool.map(lambda _: tpu.execute(sql), range(n)))
            piped = (time.perf_counter() - t0) / n
        dev_rps = total / piped

    cpu = QueryExecutor(segments, use_tpu=False, max_threads=8)
    cresp = cpu.execute(sql)
    lat = []
    for _ in range(max(2, iters // 3)):
        t0 = time.perf_counter()
        cresp = cpu.execute(sql)
        lat.append(time.perf_counter() - t0)
    host_p50 = statistics.median(lat)

    if check is not None:
        check(resp, cresp)
    used_device = len(tpu.tpu_engine._block_cache) > 0
    return {
        "rows": total,
        "device_p50_ms": round(dev_p50 * 1e3, 1),
        "device_rows_per_sec": round(dev_rps),
        "host_p50_ms": round(host_p50 * 1e3, 1),
        "host_rows_per_sec": round(total / host_p50),
        "speedup": round(dev_rps / (total / host_p50), 2),
        "device_engaged": used_device,
    }


def _approx_equal(a, b, rel=2e-3):
    fa, fb = float(a), float(b)
    return abs(fa - fb) <= rel * max(1.0, abs(fb))


def config1_baseball():
    fields = [("playerID", "STRING", False), ("yearID", "INT", False),
              ("teamID", "STRING", False), ("league", "STRING", False),
              ("runs", "INT", True), ("hits", "INT", True),
              ("homeRuns", "INT", True)]

    def cols(rng, n):
        return {
            "playerID": np.array([f"p{i}" for i in
                                  rng.integers(0, 20000, n)], object),
            "yearID": rng.integers(1871, 2014, n).astype(np.int32),
            "teamID": np.array([f"T{i}" for i in rng.integers(0, 150, n)],
                               object),
            "league": np.array([("NL", "AL")[i] for i in
                                rng.integers(0, 2, n)], object),
            "runs": rng.integers(0, 180, n).astype(np.int32),
            "hits": rng.integers(0, 260, n).astype(np.int32),
            "homeRuns": rng.integers(0, 74, n).astype(np.int32),
        }

    segs = _build("baseball", fields, cols, 4, 2_500_000)

    def check(a, b):
        assert a.result_table.rows[0][1] == b.result_table.rows[0][1]
        assert _approx_equal(a.result_table.rows[0][0],
                             b.result_table.rows[0][0])

    return _measure(segs, "SELECT SUM(runs), COUNT(*) FROM baseball", check)


def config2_ssb_q1():
    import bench
    os.makedirs(bench.DATA_DIR, exist_ok=True)
    bench.build_data()
    segs = bench.load()

    def check(a, b):
        assert a.result_table.rows[0][1] == b.result_table.rows[0][1]
        assert _approx_equal(a.result_table.rows[0][0],
                             b.result_table.rows[0][0])

    return _measure(segs, bench.QUERY, check)


def _ssb_flat_fields():
    return [("lo_orderdate", "INT", False), ("lo_discount", "INT", False),
            ("lo_quantity", "INT", False), ("d_year", "INT", False),
            ("p_category", "STRING", False), ("s_region", "STRING", False),
            ("lo_revenue", "INT", True)]


def _ssb_flat_cols(rng, n):
    return {
        "lo_orderdate": rng.integers(19920101, 19981230, n).astype(np.int32),
        "lo_discount": rng.integers(0, 11, n).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
        "d_year": rng.integers(1992, 1999, n).astype(np.int32),
        "p_category": np.array([f"MFGR#{i}" for i in
                                rng.integers(1, 6, n)], object),
        "s_region": np.array([("AMERICA", "ASIA", "EUROPE", "AFRICA")[i]
                              for i in rng.integers(0, 4, n)], object),
        "lo_revenue": rng.integers(100, 1_000_000, n).astype(np.int32),
    }


def config3_ssb_groupby():
    segs = _build("ssbgb", _ssb_flat_fields(), _ssb_flat_cols, 8, 4_000_000,
                  no_dict=("lo_revenue",))
    sql = ("SELECT d_year, p_category, SUM(lo_revenue) FROM ssbgb "
           "WHERE s_region = 'AMERICA' GROUP BY d_year, p_category "
           "ORDER BY d_year, p_category LIMIT 100")

    def check(a, b):
        ra = [(r[0], r[1]) for r in a.result_table.rows]
        rb = [(r[0], r[1]) for r in b.result_table.rows]
        assert ra == rb
        for x, y in zip(a.result_table.rows, b.result_table.rows):
            assert _approx_equal(x[2], y[2])

    return _measure(segs, sql, check)


def config4_distinct_percentile():
    fields = [("trip_id", "LONG", False), ("fare", "DOUBLE", True)]

    def cols(rng, n):
        return {
            "trip_id": rng.integers(0, 1 << 40, n).astype(np.int64),
            "fare": np.round(rng.gamma(2.5, 8.0, n), 2),
        }

    segs = _build("taxi", fields, cols, 4, 2_000_000,
                  no_dict=("trip_id", "fare"))
    sql = ("SELECT DISTINCTCOUNTHLL(trip_id), "
           "PERCENTILETDIGEST95(fare) FROM taxi")

    def check(a, b):
        # device HLL registers are bit-identical to the host sketch; the
        # device tdigest feeds histogram partials (within sketch error)
        assert a.result_table.rows[0][0] == b.result_table.rows[0][0]
        assert _approx_equal(a.result_table.rows[0][1],
                             b.result_table.rows[0][1], rel=0.02)

    return _measure(segs, sql, check, iters=3)


def config5_startree():
    from pinot_tpu.models.table_config import StarTreeIndexConfig
    st = StarTreeIndexConfig(
        dimensions_split_order=["d_year", "p_category"],
        function_column_pairs=["SUM__lo_revenue", "COUNT__*"],
        max_leaf_records=1000)
    segs = _build("ssbst", _ssb_flat_fields(), _ssb_flat_cols, 2, 2_000_000,
                  no_dict=(), star_tree=st)
    sql = ("SELECT d_year, SUM(lo_revenue) FROM ssbst "
           "GROUP BY d_year ORDER BY d_year LIMIT 100")

    from pinot_tpu.query.executor import QueryExecutor
    total = sum(s.num_docs for s in segs)
    cpu = QueryExecutor(segs, use_tpu=False, max_threads=8)
    resp = cpu.execute(sql)  # star-tree path (pre-aggregated traversal)
    t0 = time.perf_counter()
    resp = cpu.execute(sql)
    st_ms = (time.perf_counter() - t0) * 1e3
    # full-scan reference: same query with star-tree disabled via option
    sql_noopt = sql + " OPTION(useStarTree=false)"
    full = cpu.execute(sql_noopt)
    t0 = time.perf_counter()
    full = cpu.execute(sql_noopt)
    full_ms = (time.perf_counter() - t0) * 1e3
    assert [r[0] for r in resp.result_table.rows] == \
        [r[0] for r in full.result_table.rows]
    for x, y in zip(resp.result_table.rows, full.result_table.rows):
        assert _approx_equal(x[1], y[1])
    return {
        "rows": total,
        "startree_p50_ms": round(st_ms, 1),
        "fullscan_p50_ms": round(full_ms, 1),
        "speedup_vs_fullscan": round(full_ms / st_ms, 2),
        "docs_scanned_startree": resp.stats.num_docs_scanned,
        "docs_scanned_fullscan": full.stats.num_docs_scanned,
    }


def main():
    os.makedirs(DATA, exist_ok=True)
    out = {}
    for key, fn in [("baseball_sum", config1_baseball),
                    ("ssb_q1", config2_ssb_q1),
                    ("ssb_groupby", config3_ssb_groupby),
                    ("distinct_percentile", config4_distinct_percentile),
                    ("startree", config5_startree)]:
        t0 = time.time()
        try:
            out[key] = fn()
        except Exception as e:  # noqa: BLE001 — record, keep measuring
            out[key] = {"error": f"{type(e).__name__}: {e}"}
        out[key]["measure_s"] = round(time.time() - t0, 1)
        print(f"{key}: {json.dumps(out[key])}", file=sys.stderr)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_extra.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "bench_extra_configs", "value": len(out),
                      "unit": "configs", "vs_baseline": 1.0}))


if __name__ == "__main__":
    main()
