"""pinot-tpu: a TPU-native real-time distributed OLAP framework.

A ground-up rebuild of the capabilities of Apache Pinot (y-scope fork,
reference at /root/reference) designed for TPU execution: columnar immutable
segments whose scan/filter/aggregation hot path runs as jit'd JAX/Pallas
kernels sharded across a device mesh, with a host-side control plane
(SQL compilation, routing, scatter-gather reduce, ingestion, cluster
management) in Python/C++.

Layer map (mirrors SURVEY.md):
  models/    - logical data model: FieldSpec/Schema/TableConfig
               (ref: pinot-spi .../spi/data/FieldSpec.java, Schema.java,
                config/table/TableConfig.java)
  segment/   - columnar segment format: buffers, dictionaries, forward &
               auxiliary indexes, creator, loader
               (ref: pinot-segment-spi + pinot-segment-local)
  query/     - SQL front-end, per-segment planning, operators, executors,
               broker reduce (ref: pinot-core/src/.../core/{plan,operator,query})
  ops/       - JAX/Pallas device kernels (the TPU execution backend)
  parallel/  - device-mesh sharding of segment batches, collective combines
  server/    - server role: data managers, query scheduler, transport
  broker/    - broker role: routing, scatter-gather, reduce
  controller/- cluster-lite control plane (assignment, retention, tasks)
  ingest/    - batch + realtime ingestion (stream SPI, record transforms)
  utils/     - config, metrics, tracing, resource accounting
"""

__version__ = "0.1.0"
