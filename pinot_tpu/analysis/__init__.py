"""Repo-native static analysis: AST checkers run as tier-1 tests.

The system is held together by conventions no interpreter enforces:
attributes guarded by one of ~60 locks, blocking waits that must carry
the PR-3 deadline budget, seeded ``fire("site")`` chaos sites that must
stay documented + test-armed, ~100 ``pinot.*`` knobs that must exist in
the catalog and the README, and kernel-factory functions handed to
``jit``/``vmap``/``shard_map`` that must stay tracer-pure. PR 12's
exposition lint proved a tiny AST pass catches real bugs at test time
instead of under chaos load; this package generalizes it:

  * :mod:`pinot_tpu.analysis.core` — module indexer (one parsed AST +
    inline-suppression map per file), ``Finding``/``Suppression`` model,
    checker registry, committed-baseline workflow.
  * :mod:`pinot_tpu.analysis.checkers` — the repo-specific checkers
    (lock discipline, hang risk, failpoint sites, config knobs, kernel
    purity, metric exposition).
  * ``python -m pinot_tpu.analysis`` — the CLI gate: exits non-zero on
    any unsuppressed finding (``--json`` for machines, ``--baseline``
    for the committed accepted-findings file).

Suppression syntax (same line or the line directly above)::

    self._hits += 1          # lint: unlocked(meter only; torn reads ok)

Every checker has a short code (``unlocked``, ``hang``, ``failpoint``,
``knob``, ``impure``, ``exposition``, ``metricdoc``, ``errorcode``); a
suppression must carry a
non-empty reason or it does not count. Accepted pre-existing findings
live in ``ANALYSIS_BASELINE.json`` at the repo root — each entry keyed
by a line-number-independent fingerprint and a written reason, so the
gate stays green across unrelated edits but any NEW violation fails.
"""
from pinot_tpu.analysis.core import (  # noqa: F401
    Finding, ModuleIndex, Checker, CHECKERS, register,
    load_baseline, write_baseline, run_analysis, AnalysisReport,
    repo_root, default_baseline_path,
)

# importing the checkers package populates the registry
from pinot_tpu.analysis import checkers as _checkers  # noqa: F401,E402
