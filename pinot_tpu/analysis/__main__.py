"""CLI gate: ``python -m pinot_tpu.analysis``.

Exit status 0 = no unsuppressed findings; 1 = violations (or parse
errors); 2 = usage errors. Tier-1 runs this via
tests/test_static_analysis.py; CI can run it directly.

  python -m pinot_tpu.analysis                    # human output
  python -m pinot_tpu.analysis --json             # machine output
  python -m pinot_tpu.analysis --checker locks    # one checker
  python -m pinot_tpu.analysis --baseline B.json  # explicit baseline
  python -m pinot_tpu.analysis --write-baseline B.json   # bootstrap
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from pinot_tpu.analysis.core import (
    CHECKERS, ModuleIndex, default_baseline_path, load_baseline,
    run_analysis, write_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.analysis",
        description="repo-native static analysis gate")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: ANALYSIS_BASELINE.json "
                         "at the repo root when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline (raw findings)")
    ap.add_argument("--checker", action="append", default=None,
                    choices=sorted(CHECKERS),
                    help="run only this checker (repeatable)")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--write-baseline", metavar="PATH", default=None,
                    help="write current unsuppressed findings as a "
                         "baseline skeleton to PATH and exit 0")
    args = ap.parse_args(argv)

    baseline = {}
    if not args.no_baseline:
        path = args.baseline or default_baseline_path()
        if args.baseline and not os.path.exists(path):
            print(f"baseline not found: {path}", file=sys.stderr)
            return 2
        if os.path.exists(path):
            baseline = load_baseline(path)

    index = ModuleIndex(root=args.root)
    report = run_analysis(index, checkers=args.checker, baseline=baseline)

    if args.write_baseline:
        write_baseline(args.write_baseline, report.unsuppressed)
        print(f"wrote {len(report.unsuppressed)} entries to "
              f"{args.write_baseline} (reasons are TODOs — justify or "
              f"fix each one)")
        return 0

    if args.json:
        json.dump(report.to_json(), sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in report.unsuppressed:
            print(f.render())
        if report.stale_baseline:
            print(f"note: {len(report.stale_baseline)} stale baseline "
                  f"entr{'y' if len(report.stale_baseline) == 1 else 'ies'} "
                  f"(matched no finding — fixed? remove them):",
                  file=sys.stderr)
            for k in report.stale_baseline:
                print(f"  {k[0]} {k[1]} {k[2]}", file=sys.stderr)
        print(f"{len(report.unsuppressed)} unsuppressed, "
              f"{len(report.inline_suppressed)} inline-suppressed, "
              f"{len(report.baselined)} baselined "
              f"({len(report.findings)} total)")
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
