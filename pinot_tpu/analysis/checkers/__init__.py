"""The repo-specific checkers. Importing this package registers all of
them in :data:`pinot_tpu.analysis.core.CHECKERS`."""
from pinot_tpu.analysis.checkers import (  # noqa: F401
    errorcodes, exposition, failpoint_sites, hangs, knobs, locks,
    metrics_docs, purity,
)
