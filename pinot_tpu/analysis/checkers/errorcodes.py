"""Error-code registry checker: ``errorCode`` literals stay canonical.

``pinot_tpu/utils/errorcodes.py`` is the one place a query errorCode
integer may be written down (the SITES/KEYS pattern for the error
plane). This checker keeps three promises:

* **no bare ints** — every literal errorCode emission or comparison in
  production code references the catalog: flagged shapes are an int
  literal as the value of an ``"errorCode"`` dict key, an int literal
  compared against an expression mentioning ``errorCode`` (``e.get(
  "errorCode") == 250``), an int default in ``.get("errorCode", 200)``,
  an int literal as the code argument of an ``_error_response(...)``
  helper call, and ``ERROR_CODE = <int>`` class-attribute assignments;
* **no phantom codes** — every catalog name is referenced somewhere in
  production code outside the catalog module;
* **documented** — every catalog name appears in the README error-code
  table.

The catalog is parsed statically from the module AST (module-level
``NAME = <int>`` assignments plus the ``CODES`` name->description
dict); the analysis never imports production code.

Suppression code: ``errorcode``.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, call_name, register, str_const,
)

_EC_MODULE = "pinot_tpu/utils/errorcodes.py"
#: helper functions whose first positional argument is an errorCode
_CODE_ARG_HELPERS = {"_error_response", "error_response"}


def parse_registry(index: ModuleIndex
                   ) -> Optional[Tuple[Dict[str, int], Dict[str, int]]]:
    """({name: value}, {name: lineno}) from module-level NAME = <int>
    assignments in the catalog module; None when the module is gone."""
    sf = index.get(_EC_MODULE)
    if sf is None:
        return None
    values: Dict[str, int] = {}
    lines: Dict[str, int] = {}
    for node in sf.tree.body:  # module level only
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and type(node.value.value) is int:
            name = node.targets[0].id
            values[name] = node.value.value
            lines[name] = node.lineno
    return values, lines


def parse_descriptions(index: ModuleIndex) -> Optional[Set[str]]:
    """Names documented in the CODES dict."""
    sf = index.get(_EC_MODULE)
    if sf is None:
        return None
    for node in ast.walk(sf.tree):
        target = None
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            target, value = node.target.id, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        if target != "CODES" or not isinstance(value, ast.Dict):
            continue
        return {str_const(k) for k in value.keys
                if str_const(k) is not None}
    return None


def _int_const(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    return None


def _mentions_errorcode(node: ast.AST) -> bool:
    """True when the expression textually involves an errorCode lookup
    (``x["errorCode"]``, ``x.get("errorCode")``, a name containing
    ERROR_CODE...)."""
    for sub in ast.walk(node):
        s = str_const(sub)
        if s == "errorCode":
            return True
        if isinstance(sub, ast.Name) and "ERROR_CODE" in sub.id:
            return True
        if isinstance(sub, ast.Attribute) and "ERROR_CODE" in sub.attr:
            return True
    return False


@register
class ErrorCodeChecker(Checker):
    name = "errorcodes"
    code = "errorcode"

    def run(self, index: ModuleIndex) -> List[Finding]:
        reg = parse_registry(index)
        ec_sf = index.get(_EC_MODULE)
        if reg is None or ec_sf is None:
            # the catalog vanishing is itself drift — but only report
            # when the tree looks like the real package (fixture trees
            # in the unit tests have no catalog at all)
            acct = index.get("pinot_tpu/utils/accounting.py")
            if acct is not None:
                return [Finding(
                    checker=self.name, code=self.code,
                    file="pinot_tpu/utils/accounting.py", line=1,
                    key="registry:missing",
                    message="utils/errorcodes.py registry not found — "
                            "the canonical errorCode catalog is gone")]
            return []
        values, reg_lines = reg
        described = parse_descriptions(index) or set()
        out: List[Finding] = []
        referenced: Set[str] = set()
        for sf in index.files("pinot_tpu/"):
            if sf.relpath == _EC_MODULE:
                continue
            for node in ast.walk(sf.tree):
                # references to catalog names (leg 2's evidence)
                if isinstance(node, ast.Attribute) \
                        and node.attr in values:
                    referenced.add(node.attr)
                elif isinstance(node, ast.Name) and node.id in values:
                    referenced.add(node.id)
                # violation shapes (leg 1)
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if str_const(k) == "errorCode" \
                                and _int_const(v) is not None:
                            out.append(self.finding(
                                sf, v,
                                key=f"literal:dict:{_int_const(v)}",
                                message=(
                                    f'literal errorCode {_int_const(v)} '
                                    f"in a dict emission — reference "
                                    f"utils/errorcodes.py instead")))
                elif isinstance(node, ast.Compare):
                    sides = [node.left, *node.comparators]
                    ints = [s for s in sides
                            if _int_const(s) is not None]
                    if ints and _mentions_errorcode(node):
                        out.append(self.finding(
                            sf, node,
                            key=f"literal:cmp:{_int_const(ints[0])}",
                            message=(
                                f"literal errorCode "
                                f"{_int_const(ints[0])} in a comparison "
                                f"— reference utils/errorcodes.py "
                                f"instead")))
                elif isinstance(node, ast.Call):
                    fn = call_name(node)
                    if fn.split(".")[-1] in _CODE_ARG_HELPERS \
                            and node.args \
                            and _int_const(node.args[0]) is not None:
                        out.append(self.finding(
                            sf, node,
                            key=(f"literal:call:"
                                 f"{_int_const(node.args[0])}"),
                            message=(
                                f"literal errorCode "
                                f"{_int_const(node.args[0])} passed to "
                                f"{fn}() — reference "
                                f"utils/errorcodes.py instead")))
                    elif fn.endswith(".get") and len(node.args) >= 2 \
                            and str_const(node.args[0]) == "errorCode" \
                            and _int_const(node.args[1]) is not None:
                        out.append(self.finding(
                            sf, node,
                            key=(f"literal:default:"
                                 f"{_int_const(node.args[1])}"),
                            message=(
                                f"literal errorCode default "
                                f"{_int_const(node.args[1])} in "
                                f'.get("errorCode", ...) — reference '
                                f"utils/errorcodes.py instead")))
                elif isinstance(node, ast.Assign) \
                        and _int_const(node.value) is not None:
                    for t in node.targets:
                        tname = (t.id if isinstance(t, ast.Name)
                                 else t.attr if isinstance(t, ast.Attribute)
                                 else "")
                        if "ERROR_CODE" in tname:
                            out.append(self.finding(
                                sf, node,
                                key=f"literal:assign:{tname}",
                                message=(
                                    f"literal errorCode assigned to "
                                    f"{tname} — reference "
                                    f"utils/errorcodes.py instead")))
        for name in sorted(values):
            if name not in referenced:
                out.append(self.finding(
                    ec_sf, reg_lines[name], key=f"dead:{name}",
                    message=(f'errorcodes.{name} is referenced nowhere '
                             f"in production code — phantom code")))
            if name not in described:
                out.append(self.finding(
                    ec_sf, reg_lines[name], key=f"undescribed:{name}",
                    message=(f'errorcodes.{name} has no CODES registry '
                             f"description — the README table renders "
                             f"from it")))
        readme = os.path.join(index.root, "README.md")
        if os.path.exists(readme):
            with open(readme, encoding="utf-8") as f:
                readme_text = f.read()
            for name in sorted(values):
                if name not in readme_text:
                    out.append(self.finding(
                        ec_sf, reg_lines[name],
                        key=f"undocumented:{name}",
                        message=(f'errorcodes.{name} appears in no '
                                 f"README error-code table — clients "
                                 f"cannot discover it")))
        return out
