"""Metric-exposition lint (the PR-12 checker, framework edition).

A metric name emitted as two different kinds (counter in one file,
gauge in another) produces two ``# TYPE`` families for one name —
invalid exposition that Prometheus scrapers reject WHOLESALE, taking
every other metric on the page down with it. This scans every literal
metric emission in the package; dynamically composed names (f-strings
with prefixes) are out of scope — they are namespaced by construction
(``metric_prefix`` / ``remote_cache_``).

Suppression code: ``exposition`` (on the first emission site).
"""
from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, register,
)

KINDS = {
    "add_meter": "counter", "_meter": "counter",
    "set_gauge": "gauge",
    "add_timing": "timer", "time": "timer", "observe": "timer",
}
#: \s* spans newlines, so emissions whose name literal wraps to the
#: line after the open paren are linted too — the scan runs over the
#: whole source, never line-by-line
PATTERN = re.compile(
    r'\.(add_meter|set_gauge|add_timing|observe|_meter|time)\('
    r'\s*"([A-Za-z_][A-Za-z0-9_]*)"')


@register
class ExpositionChecker(Checker):
    name = "exposition"
    code = "exposition"

    def run(self, index: ModuleIndex) -> List[Finding]:
        uses: Dict[str, Set[str]] = {}
        # name -> [(sf, line, call)]
        sites: Dict[str, List[Tuple]] = {}
        for sf in index.files("pinot_tpu/"):
            for m in PATTERN.finditer(sf.source):
                call, name = m.groups()
                line = sf.source.count("\n", 0, m.start()) + 1
                uses.setdefault(name, set()).add(KINDS[call])
                sites.setdefault(name, []).append((sf, line, call))
        out: List[Finding] = []
        if not uses:
            # regex rot guard: an exposition lint that scans nothing is
            # itself a finding, not a green check
            files = index.files("pinot_tpu/")
            if files:
                out.append(self.finding(
                    files[0], 1, key="scan:empty",
                    message="exposition lint matched zero metric "
                            "emissions — pattern rot?"))
            return out
        for name, kinds in sorted(uses.items()):
            if len(kinds) <= 1:
                continue
            sf, line, _call = sites[name][0]
            where = ", ".join(f"{s.relpath}:{ln} ({c})"
                              for s, ln, c in sites[name])
            out.append(self.finding(
                sf, line, key=f"dup-kind:{name}",
                message=(f"metric name '{name}' emitted as multiple "
                         f"kinds {sorted(kinds)} — invalid exposition "
                         f"(scrapers reject the whole page): {where}")))
        return out
