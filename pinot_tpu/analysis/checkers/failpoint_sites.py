"""Failpoint-site registry checker.

``pinot_tpu/utils/failpoints.py`` carries the canonical ``SITES`` table
— site name -> one-line description. This checker keeps three promises:

  * every ``fire("<site>")`` literal compiled into production code is a
    documented SITES entry (no drive-by chaos hooks that nobody can
    discover or arm);
  * every SITES entry is fired somewhere (no phantom documentation for
    sites that were refactored away);
  * every SITES entry is ARMED by at least one test — the site's string
    literal appears under ``tests/`` (an ``arm(...)``/``armed(...)``
    call or a FaultSchedule entry). A chaos hook no test ever pulls is
    dead weight pretending to be coverage.

The table is parsed statically from the module AST (the analysis never
imports production code), so a site added to SITES with a typo fails
the fired-somewhere leg immediately.

Suppression code: ``failpoint``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, call_name, register, str_const,
)

_FP_MODULE = "pinot_tpu/utils/failpoints.py"
_FIRE_NAMES = {"fire", "failpoints.hit"}


def parse_sites(index: ModuleIndex) -> Optional[Dict[str, str]]:
    sf = index.get(_FP_MODULE)
    if sf is None:
        return None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SITES":
            dct = node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == "SITES":
            dct = node.value
        else:
            continue
        if not isinstance(dct, ast.Dict):
            continue
        out: Dict[str, str] = {}
        for k, v in zip(dct.keys, dct.values):
            ks, vs = str_const(k), str_const(v)
            if ks is not None:
                out[ks] = vs or ""
        return out
    return None


def fired_sites(index: ModuleIndex) -> Dict[str, List]:
    """site -> [(SourceFile, lineno), ...] across production code."""
    out: Dict[str, List] = {}
    for sf in index.files("pinot_tpu/"):
        if sf.relpath == _FP_MODULE:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) in _FIRE_NAMES and node.args:
                site = str_const(node.args[0])
                if site is not None:
                    out.setdefault(site, []).append((sf, node.lineno))
    return out


def test_literals(index: ModuleIndex) -> Set[str]:
    out: Set[str] = set()
    for sf in index.files("tests/"):
        for node in ast.walk(sf.tree):
            s = str_const(node)
            if s is not None:
                out.add(s)
    return out


@register
class FailpointSiteChecker(Checker):
    name = "failpoints"
    code = "failpoint"

    def run(self, index: ModuleIndex) -> List[Finding]:
        fp_sf = index.get(_FP_MODULE)
        if fp_sf is None:
            return []
        sites = parse_sites(index)
        if sites is None:
            return [self.finding(
                fp_sf, 1, key="SITES:missing",
                message="utils/failpoints.py has no SITES dict — the "
                        "canonical site registry is gone")]
        fired = fired_sites(index)
        armed = test_literals(index)
        out: List[Finding] = []
        for site, locs in sorted(fired.items()):
            if site not in sites:
                sf, line = locs[0]
                out.append(self.finding(
                    sf, line, key=f"undocumented:{site}",
                    message=(f'fire("{site}") is not in the canonical '
                             f"SITES table in utils/failpoints.py — "
                             f"document it (and arm it in a test)")))
        for site in sorted(sites):
            if site not in fired:
                out.append(self.finding(
                    fp_sf, 1, key=f"dead:{site}",
                    message=(f'SITES documents "{site}" but no '
                             f'fire("{site}") exists in production '
                             f"code — stale registry entry")))
            elif site not in armed:
                sf, line = fired[site][0]
                out.append(self.finding(
                    sf, line, key=f"unarmed:{site}",
                    message=(f'failpoint site "{site}" is never armed '
                             f"by any test (its literal appears "
                             f"nowhere under tests/) — chaos coverage "
                             f"gap")))
        return out
