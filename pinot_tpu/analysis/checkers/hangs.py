"""Hang-risk lint: unbounded blocking waits on query-serving paths.

A query must die by its deadline, never hang: the PR-3 reliability work
made every broker/server wait deadline-derived, and this checker keeps
it that way. On the query-serving modules (broker, query, mse, ops,
server, client, netframe) it flags:

  * ``fut.result()`` with neither a positional nor ``timeout=``
    argument — a future whose producer dies strands the caller forever
    (the dispatch ring promises to complete every popped future, but
    that invariant lives a module away; the wait must be bounded
    locally by the query's remaining budget);
  * ``ev.wait()`` / ``cv.wait()`` with no timeout;
  * ``q.get()`` with no timeout on a queue-like receiver (name matches
    queue/mailbox/inbox) unless called non-blocking;
  * ``sock.recv()/recvfrom()`` in a module with no visible socket
    timeout discipline (no ``settimeout`` call and no
    ``create_connection(..., timeout=...)``).

Suppression code: ``hang`` —
``packed = fut.result()  # lint: hang(producer completes every future)``
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, dotted, kwarg_names, register,
)

_SCOPES = ("pinot_tpu/broker/", "pinot_tpu/query/", "pinot_tpu/mse/",
           "pinot_tpu/ops/", "pinot_tpu/server/", "pinot_tpu/client/",
           "pinot_tpu/utils/netframe.py")
_QUEUEISH = re.compile(r"(queue|mailbox|inbox)", re.IGNORECASE)


def _has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or "timeout" in kwarg_names(call)


def _module_has_socket_timeout(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name.endswith("settimeout"):
            return True
        if name.endswith("create_connection") and (
                len(node.args) >= 2 or "timeout" in kwarg_names(node)):
            return True
    return False


@register
class HangRiskChecker(Checker):
    name = "hangs"
    code = "hang"

    def run(self, index: ModuleIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files("pinot_tpu/"):
            if not any(sf.relpath.startswith(s) or sf.relpath == s
                       for s in _SCOPES):
                continue
            sock_ok = _module_has_socket_timeout(sf.tree)
            # enclosing-function names for stable keys
            func_of: Dict[int, str] = {}
            for fn in [n for n in ast.walk(sf.tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]:
                for sub in ast.walk(fn):
                    if hasattr(sub, "lineno"):
                        func_of.setdefault(id(sub), fn.name)
            dup: Dict[str, int] = {}
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                recv = dotted(node.func.value) or \
                    type(node.func.value).__name__
                msg = None
                if attr == "result" and not _has_timeout(node):
                    msg = (f"unbounded {recv}.result() — bound the wait "
                           f"with the query's remaining deadline budget")
                elif attr == "wait" and not _has_timeout(node):
                    msg = (f"unbounded {recv}.wait() — pass a timeout "
                           f"derived from the deadline")
                elif attr == "get" and _QUEUEISH.search(recv) \
                        and not _has_timeout(node) \
                        and not any(k in ("block",)
                                    for k in kwarg_names(node)):
                    msg = (f"unbounded {recv}.get() on a queue — pass "
                           f"timeout= or block=False")
                elif attr in ("recv", "recvfrom") and not sock_ok:
                    msg = (f"{recv}.{attr}() in a module with no "
                           f"settimeout/timeout= socket discipline")
                if msg is None:
                    continue
                fn = func_of.get(id(node), "<module>")
                base = f"{fn}:{recv}.{attr}"
                n = dup.get(base, 0)
                dup[base] = n + 1
                key = base if n == 0 else f"{base}#{n + 1}"
                out.append(self.finding(sf, node, key=key, message=msg))
        return out
