"""Config-knob checker: the ``pinot.*`` catalog stays typo-proof.

Three legs, catching dead knobs in both directions:

  * every literal key passed to a config getter (``cfg.get*("pinot.…")``
    / ``cfg.is_set``) in production or bench code must exist in the
    ``KEYS`` catalog in ``utils/config.py`` — a typo'd read silently
    returns the getter default and the knob does nothing;
  * every catalog key must be READ somewhere in production/bench code
    (its literal appears outside config.py) — a knob nothing reads is
    documentation of behavior that does not exist;
  * every catalog key must appear in a README knob table — operators
    discover knobs there, not by reading the catalog source.

Dynamically composed keys (``"pinot.broker.timeout.ms." + table``,
f-strings) are out of scope by construction — only literal first
arguments are checked, and the composed families' base keys are
catalog entries already.

Suppression code: ``knob``.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, register, str_const,
)

_CFG_MODULE = "pinot_tpu/utils/config.py"
_GETTERS = {"get", "get_int", "get_float", "get_bool", "get_str",
            "is_set"}


def parse_catalog(index: ModuleIndex) -> Optional[Dict[str, int]]:
    """KEYS knob -> line number, parsed statically."""
    sf = index.get(_CFG_MODULE)
    if sf is None:
        return None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == "KEYS" \
                and isinstance(node.value, ast.Dict):
            dct = node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "KEYS" \
                and isinstance(node.value, ast.Dict):
            dct = node.value
        else:
            continue
        out: Dict[str, int] = {}
        for k in dct.keys:
            ks = str_const(k)
            if ks is not None:
                out[ks] = k.lineno
        return out
    return None


@register
class ConfigKnobChecker(Checker):
    name = "knobs"
    code = "knob"

    def run(self, index: ModuleIndex) -> List[Finding]:
        catalog = parse_catalog(index)
        cfg_sf = index.get(_CFG_MODULE)
        if catalog is None or cfg_sf is None:
            return []
        scoped = [sf for sf in index.files()
                  if (sf.relpath.startswith("pinot_tpu/")
                      or sf.relpath.startswith("bench"))]
        out: List[Finding] = []
        read_literals: Set[str] = set()
        for sf in scoped:
            if sf.relpath == _CFG_MODULE:
                continue
            for node in ast.walk(sf.tree):
                s = str_const(node)
                if s is not None and s.startswith("pinot."):
                    read_literals.add(s)
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _GETTERS and node.args:
                    key = str_const(node.args[0])
                    if key is None or not key.startswith("pinot."):
                        continue
                    if key not in catalog:
                        out.append(self.finding(
                            sf, node, key=f"unknown:{key}",
                            message=(f'config read of "{key}" which is '
                                     f"not in the utils/config.py KEYS "
                                     f"catalog — typo'd knob reads "
                                     f"fall through to the getter "
                                     f"default silently")))
        readme = os.path.join(index.root, "README.md")
        readme_text = ""
        if os.path.exists(readme):
            with open(readme, encoding="utf-8") as f:
                readme_text = f.read()
        for key, line in sorted(catalog.items()):
            if key not in read_literals:
                out.append(self.finding(
                    cfg_sf, line, key=f"dead:{key}",
                    message=(f'catalog knob "{key}" is read nowhere in '
                             f"production or bench code — dead knob")))
            if readme_text and key not in readme_text:
                out.append(self.finding(
                    cfg_sf, line, key=f"undocumented:{key}",
                    message=(f'catalog knob "{key}" appears in no '
                             f"README knob table — operators cannot "
                             f"discover it")))
        return out
