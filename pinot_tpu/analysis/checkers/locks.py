"""Lock-discipline race detector.

Per class, infer the set of attributes the class treats as lock-guarded
— any ``self.X`` WRITTEN at least once inside a lock scope outside
``__init__`` — then flag every read or write of those attributes
outside any lock scope. Two things count as a lock scope:

  * the body of ``with self.<lock>:`` where ``<lock>`` is a lock-like
    attribute (assigned from ``threading.Lock/RLock/Condition`` in any
    method, or name-matching ``lock|cv|cond|mutex``), including
    multi-item withs;
  * the body of a method whose name ends in ``_locked`` — the repo-wide
    convention for "caller must hold the lock" helpers.

The inference deliberately keys on WRITES under lock: an attribute only
ever read under a lock (config captured in ``__init__``, say) is not
shared mutable state, and flagging it would drown the signal. A nested
NAMED function defined inside a lock scope gets depth 0 — closures run
later, on other threads, when the lock is long released (that is
precisely the race class this checker exists for) — while lambdas
inherit the enclosing depth (``sorted(key=...)`` / default-arg lambdas
run synchronously under the lock that encloses them).

Calls to ``self.<name>_locked()`` from outside a lock scope are flagged
too: the suffix is a contract, and an unlocked call site breaks it.

Suppression code: ``unlocked`` —
``self._hits += 1  # lint: unlocked(monotonic meter; torn read benign)``
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, SourceFile, dotted, register,
)

_LOCK_NAME_HINTS = ("lock", "_cv", "cond", "mutex")
_LOCK_CTORS = {"Lock", "RLock", "Condition", "threading.Lock",
               "threading.RLock", "threading.Condition"}
_CTOR_METHODS = {"__init__", "__new__", "__post_init__", "__set_name__"}
#: attribute calls treated as writes to the receiver (mutating a
#: guarded container is a write to the guarded state)
_MUTATORS = {"append", "extend", "add", "update", "remove", "discard",
             "pop", "popitem", "clear", "insert", "setdefault",
             "appendleft", "popleft", "sort"}


def _is_lock_attr(name: str, ctor_assigned: Set[str]) -> bool:
    if name in ctor_assigned:
        return True
    low = name.lower()
    return any(h in low for h in _LOCK_NAME_HINTS)


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' when node is ``self.X``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _Access:
    __slots__ = ("attr", "kind", "line", "depth", "method")

    def __init__(self, attr: str, kind: str, line: int, depth: int,
                 method: str):
        self.attr = attr
        self.kind = kind          # 'read' | 'write'
        self.line = line
        self.depth = depth        # lock-nesting depth at the access
        self.method = method


class _MethodScanner(ast.NodeVisitor):
    """Walk ONE method body tracking lock depth; collect accesses and
    unlocked ``*_locked()`` helper calls."""

    def __init__(self, method: str, lock_attrs: Set[str], base_depth: int):
        self.method = method
        self.lock_attrs = lock_attrs
        self.depth = base_depth
        self.accesses: List[_Access] = []
        self.locked_calls: List[Tuple[str, int, int]] = []  # name, line, depth

    # -- scopes --------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        takes = 0
        for item in node.items:
            for sub in ast.walk(item.context_expr):
                a = _self_attr(sub)
                if a is not None and a in self.lock_attrs:
                    takes = 1
            # the header expression itself evaluates OUTSIDE the lock
            self.visit(item.context_expr)
        self.depth += takes
        for stmt in node.body:
            self.visit(stmt)
        self.depth -= takes

    visit_AsyncWith = visit_With

    def _nested(self, node) -> None:
        # closure bodies run later, lock released: depth resets to 0
        saved = self.depth
        self.depth = 0
        for stmt in getattr(node, "body", []):
            self.visit(stmt) if isinstance(stmt, ast.stmt) else None
        self.depth = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # lambdas INHERIT depth: the overwhelmingly common shapes
        # (sorted key=, dict.get default=) run synchronously under the
        # lock that encloses them — unlike named closures, which are
        # the deferred-callback idiom here
        self.visit(node.body)

    # -- accesses ------------------------------------------------------
    def _record(self, attr: str, kind: str, line: int) -> None:
        if attr in self.lock_attrs:
            return
        self.accesses.append(
            _Access(attr, kind, line, self.depth, self.method))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        a = _self_attr(node)
        if a is not None:
            kind = "write" if isinstance(node.ctx,
                                         (ast.Store, ast.Del)) else "read"
            self._record(a, kind, node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self.X[k] = v / del self.X[k]: a write to guarded X
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            a = _self_attr(node.value)
            if a is not None:
                self._record(a, "write", node.lineno)
                self.visit(node.slice)
                return
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        a = _self_attr(node.target)
        if a is not None:
            # += is a read-modify-write: record as write (the racier half)
            self._record(a, "write", node.lineno)
        else:
            self.visit(node.target)
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        # self.X.append(...) mutates guarded X; self.helper_locked()
        # outside a lock breaks the suffix contract
        if isinstance(node.func, ast.Attribute):
            recv = _self_attr(node.func.value)
            if recv is not None and node.func.attr in _MUTATORS:
                self._record(recv, "write", node.lineno)
            helper = _self_attr(node.func)
            if helper is not None and helper.endswith("_locked"):
                self.locked_calls.append(
                    (helper, node.lineno, self.depth))
        self.generic_visit(node)


@register
class LockDisciplineChecker(Checker):
    name = "locks"
    code = "unlocked"

    def run(self, index: ModuleIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files("pinot_tpu/"):
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    out.extend(self._check_class(sf, node))
        return out

    # ------------------------------------------------------------------
    def _check_class(self, sf: SourceFile,
                     cls: ast.ClassDef) -> List[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        if not methods:
            return []
        # lock attrs: ctor-assigned lock objects + name heuristic on
        # every `with self.X:` target
        ctor_assigned: Set[str] = set()
        for m in methods:
            for n in ast.walk(m):
                if isinstance(n, ast.Assign) and isinstance(n.value,
                                                            ast.Call):
                    ctor = dotted(n.value.func)
                    if ctor in _LOCK_CTORS:
                        for t in n.targets:
                            a = _self_attr(t)
                            if a is not None:
                                ctor_assigned.add(a)
        lock_attrs: Set[str] = set(ctor_assigned)
        for m in methods:
            for n in ast.walk(m):
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        for sub in ast.walk(item.context_expr):
                            a = _self_attr(sub)
                            if a is not None and _is_lock_attr(
                                    a, ctor_assigned):
                                lock_attrs.add(a)
        if not lock_attrs:
            return []

        accesses: List[_Access] = []
        locked_calls: List[Tuple[str, int, int, str]] = []
        for m in methods:
            base = 1 if m.name.endswith("_locked") else 0
            sc = _MethodScanner(m.name, lock_attrs, base)
            for stmt in m.body:
                sc.visit(stmt)
            accesses.extend(sc.accesses)
            locked_calls.extend((h, ln, d, m.name)
                                for h, ln, d in sc.locked_calls)

        guarded = {a.attr for a in accesses
                   if a.kind == "write" and a.depth > 0
                   and a.method not in _CTOR_METHODS}
        out: List[Finding] = []
        seen: Set[Tuple[str, str, str, str]] = set()
        for a in accesses:
            if a.attr not in guarded or a.depth > 0 \
                    or a.method in _CTOR_METHODS:
                continue
            ident = (cls.name, a.attr, a.method, a.kind)
            if ident in seen:
                continue
            seen.add(ident)
            out.append(self.finding(
                sf, a.line,
                key=f"{cls.name}.{a.attr}:{a.kind}@{a.method}",
                message=(f"{a.kind} of lock-guarded attribute "
                         f"'{a.attr}' outside any lock scope in "
                         f"{cls.name}.{a.method} (attribute is written "
                         f"under a lock elsewhere in the class)")))
        for helper, line, depth, method in locked_calls:
            if depth > 0 or method.endswith("_locked") \
                    or method in _CTOR_METHODS:
                continue
            ident = (cls.name, helper, method, "call")
            if ident in seen:
                continue
            seen.add(ident)
            out.append(self.finding(
                sf, line,
                key=f"{cls.name}.{helper}:call@{method}",
                message=(f"call of under-lock helper '{helper}' from "
                         f"{cls.name}.{method} outside any lock scope "
                         f"(the _locked suffix is a held-lock "
                         f"contract)")))
        return out
