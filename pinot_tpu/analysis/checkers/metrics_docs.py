"""Metric-docs checker: the metric-name catalog stays drift-proof.

The exposition analog of the ``knobs`` checker, with the same
both-direction dead-entry detection:

  * every LITERAL metric name emitted through a registry
    (``add_meter`` / ``set_gauge`` / ``add_timing`` / ``time`` /
    ``observe`` / pass-through ``_meter`` helpers) must have an entry in
    the ``METRICS`` catalog in ``utils/metrics_catalog.py`` — an
    uncataloged metric ships with no ``# HELP`` line and no docs;
  * every catalog entry must be EMITTED somewhere in ``pinot_tpu/`` — a
    catalog row nothing emits documents a series that does not exist;
  * every catalog entry must appear in a README metrics-reference table
    — operators discover series there, not in the catalog source.

Prefix-composed emissions are namespaced by construction and OUT of
scope: a ``_meter``/``_gauge_bytes``-style helper whose body builds the
name with an f-string (``f"{prefix}_{name}"``, cache/core.py) marks its
call-site literals as suffixes, not family names — detected statically
from the helper's own def in the same module. Dynamically composed
names passed to the registry directly (f-strings at the call site) are
likewise skipped; only plain string literals are checked.

Suppression code: ``metricdoc``.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, register, str_const,
)

_CATALOG_MODULE = "pinot_tpu/utils/metrics_catalog.py"
#: registry methods whose literal first argument is a metric family name
_EMITTERS = {"add_meter", "set_gauge", "add_timing", "time", "observe",
             "remove_gauge", "set_exemplar", "meter", "_meter"}


def parse_metrics_catalog(index: ModuleIndex) -> Optional[Dict[str, int]]:
    """METRICS metric name -> line number, parsed statically."""
    sf = index.get(_CATALOG_MODULE)
    if sf is None:
        return None
    for node in ast.walk(sf.tree):
        target = None
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            target, value = node.target.id, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        if target != "METRICS" or not isinstance(value, ast.Dict):
            continue
        out: Dict[str, int] = {}
        for k in value.keys:
            ks = str_const(k)
            if ks is not None:
                out[ks] = k.lineno
        return out
    return None


def _composing_helpers(tree: ast.AST) -> Set[str]:
    """Names of module-local methods that COMPOSE the metric name
    (f-string in their body reaching a registry call) — their call-site
    literals are namespaced suffixes, not family names."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in _EMITTERS:
            continue
        for inner in ast.walk(node):
            if isinstance(inner, ast.JoinedStr):
                out.add(node.name)
                break
    return out


@register
class MetricsDocsChecker(Checker):
    name = "metrics_docs"
    code = "metricdoc"

    def run(self, index: ModuleIndex) -> List[Finding]:
        catalog = parse_metrics_catalog(index)
        cat_sf = index.get(_CATALOG_MODULE)
        if catalog is None or cat_sf is None:
            # the catalog module vanishing is itself drift — but the
            # fixture trees the unit tests build have no catalog at all;
            # report only when the package looks real (has the registry)
            reg_sf = index.get("pinot_tpu/utils/metrics.py")
            if reg_sf is not None:
                return [self.finding(
                    reg_sf, 1, key="catalog:missing",
                    message="utils/metrics_catalog.py METRICS catalog "
                            "not found — # HELP exposition and the "
                            "README metrics reference have no source")]
            return []
        emitted: Dict[str, List[Tuple]] = {}
        scanned = 0
        for sf in index.files("pinot_tpu/"):
            if sf.relpath == _CATALOG_MODULE:
                continue
            composing = _composing_helpers(sf.tree)
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _EMITTERS and node.args):
                    continue
                if node.func.attr in composing:
                    continue  # namespaced by construction
                arg = node.args[0]
                # conditional names ("hedge_won" if won else
                # "hedge_wasted") emit BOTH branches' literals
                branches = ([arg.body, arg.orelse]
                            if isinstance(arg, ast.IfExp) else [arg])
                names = [n for n in map(str_const, branches)
                         if n is not None]
                if not names:
                    continue  # dynamically composed — out of scope
                scanned += 1
                for name in names:
                    emitted.setdefault(name, []).append((sf, node))
        if not emitted:
            files = index.files("pinot_tpu/")
            if files:
                return [self.finding(
                    files[0], 1, key="scan:empty",
                    message="metrics-docs scan matched zero literal "
                            "metric emissions — pattern rot?")]
            return []
        out: List[Finding] = []
        for name, sites in sorted(emitted.items()):
            if name not in catalog:
                sf, node = sites[0]
                out.append(self.finding(
                    sf, node, key=f"uncataloged:{name}",
                    message=(f'metric "{name}" is emitted but has no '
                             f"METRICS catalog entry "
                             f"(utils/metrics_catalog.py) — it ships "
                             f"with no # HELP line and no docs")))
        readme = os.path.join(index.root, "README.md")
        readme_text = ""
        if os.path.exists(readme):
            with open(readme, encoding="utf-8") as f:
                readme_text = f.read()
        for name, line in sorted(catalog.items()):
            if name not in emitted:
                out.append(self.finding(
                    cat_sf, line, key=f"dead:{name}",
                    message=(f'catalog metric "{name}" is emitted '
                             f"nowhere in pinot_tpu/ — dead entry")))
            if readme_text and name not in readme_text:
                out.append(self.finding(
                    cat_sf, line, key=f"undocumented:{name}",
                    message=(f'catalog metric "{name}" appears in no '
                             f"README metrics-reference table — "
                             f"operators cannot discover it")))
        return out
