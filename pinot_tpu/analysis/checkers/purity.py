"""Kernel-purity checker: jit'd factory functions stay tracer-pure.

Functions handed to ``jax.jit`` / ``jax.vmap`` / ``shard_map`` in
``ops/kernels.py`` execute at TRACE time and are then replayed as a
compiled program: a ``time.*`` / ``random.*`` / ``np.random.*`` call
inside one bakes a constant into the kernel (silently wrong), and a
host-sync (``block_until_ready``, ``.item()``, ``np.asarray`` on a
device value, ``jax.device_get``) inside one stalls the trace or
retraces per call. Host syncs belong to the dispatch/fetch layer
(``ops/dispatch.py`` — and ``ops/engine.py``'s assemble path), never
inside the kernel factory.

Resolution follows the factory idiom: the first argument of a
jit/vmap/shard_map call is a lambda (checked inline), a local function
name, or a ``make_*(plan)`` call — in which case every inner function
of the factory is treated as traced. The traced set then closes over
module-local calls (helpers like ``_eval_filter`` are traced too).

A helper that is DELIBERATELY impure at trace time only (the
``note_trace`` compile odometer) is vetted wholesale by a suppression
on its ``def`` line: ``def note_trace(...):  # lint: impure(reason)``
— the checker neither flags its body nor descends into it.

Also flagged, module-wide in ``ops/`` (outside the dispatch/fetch
modules): ``block_until_ready`` / ``device_get`` calls — the dispatch
ring owns device synchronization; a stray sync elsewhere serializes
the pipelined path.

Suppression code: ``impure``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.analysis.core import (
    Checker, Finding, ModuleIndex, SourceFile, call_name, register,
)

_KERNEL_MODULES = ("pinot_tpu/ops/kernels.py",
                   "pinot_tpu/ops/startree_device.py",
                   "pinot_tpu/ops/clp_device.py",
                   "pinot_tpu/ops/collective.py",
                   "pinot_tpu/ops/vector_device.py",
                   "pinot_tpu/ops/timeseries_device.py")
#: modules that own device synchronization — host syncs are their job
_SYNC_OK = {"pinot_tpu/ops/dispatch.py", "pinot_tpu/ops/engine.py",
            "pinot_tpu/ops/residency.py"}
_JIT_WRAPPERS = {"jax.jit", "jit", "jax.vmap", "vmap", "shard_map",
                 "jax.experimental.shard_map.shard_map"}
_BANNED_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "datetime.")
_BANNED_EXACT = {"time", "print"}
_HOST_SYNC = {"jax.block_until_ready", "block_until_ready",
              "jax.device_get", "device_get", "np.asarray",
              "numpy.asarray", "np.array", "numpy.array"}


def _first_arg_functions(call: ast.Call, by_name: Dict[str, List],
                         ) -> Tuple[List, List[ast.Lambda]]:
    """Resolve a jit/vmap/shard_map first argument to candidate traced
    FunctionDefs (and/or lambdas)."""
    if not call.args:
        return [], []
    arg = call.args[0]
    if isinstance(arg, ast.Lambda):
        return [], [arg]
    if isinstance(arg, ast.Name):
        return list(by_name.get(arg.id, [])), []
    if isinstance(arg, ast.Call):
        # make_kernel(plan): every inner def of the factory is traced
        target = call_name(arg)
        fns = []
        for f in by_name.get(target, []):
            fns.extend(n for n in ast.walk(f)
                       if isinstance(n, ast.FunctionDef) and n is not f)
        return fns, []
    return [], []


@register
class KernelPurityChecker(Checker):
    name = "purity"
    code = "impure"

    def run(self, index: ModuleIndex) -> List[Finding]:
        out: List[Finding] = []
        for mod in _KERNEL_MODULES:
            sf = index.get(mod)
            if sf is not None:
                out.extend(self._check_kernels(sf))
        for sf in index.files("pinot_tpu/ops/"):
            if sf.relpath in _SYNC_OK or sf.relpath in _KERNEL_MODULES:
                continue
            out.extend(self._check_stray_syncs(sf))
        return out

    # ------------------------------------------------------------------
    def _check_kernels(self, sf: SourceFile) -> List[Finding]:
        by_name: Dict[str, List] = {}
        module_names: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef):
                by_name.setdefault(node.name, []).append(node)
        for node in sf.tree.body:  # type: ignore[attr-defined]
            for t in (node.targets if isinstance(node, ast.Assign) else
                      [node.target] if isinstance(node, ast.AnnAssign)
                      else []):
                if isinstance(t, ast.Name):
                    module_names.add(t.id)

        traced: List = []
        traced_ids: Set[int] = set()
        lambdas: List[ast.Lambda] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) in _JIT_WRAPPERS:
                fns, lams = _first_arg_functions(node, by_name)
                for f in fns:
                    if id(f) not in traced_ids:
                        traced_ids.add(id(f))
                        traced.append(f)
                lambdas.extend(lams)

        # close over module-local calls; a def-line 'impure' suppression
        # vets the helper wholesale (trace-time-only by argument)
        i = 0
        while i < len(traced):
            fn = traced[i]
            i += 1
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = call_name(node)
                    for f in by_name.get(callee, []):
                        if id(f) in traced_ids:
                            continue
                        if sf.suppressed(f.lineno, self.code):
                            continue
                        traced_ids.add(id(f))
                        traced.append(f)

        out: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        for fn in traced:
            out.extend(self._check_body(sf, fn.name, fn, module_names,
                                        seen))
        for lam in lambdas:
            out.extend(self._check_body(sf, f"<lambda:{lam.lineno}>",
                                        lam, module_names, seen))
        return out

    def _check_body(self, sf: SourceFile, name: str, fn,
                    module_names: Set[str],
                    seen: Set[Tuple[str, str]]) -> List[Finding]:
        out: List[Finding] = []

        def emit(node, what: str, why: str) -> None:
            ident = (name, what)
            if ident in seen:
                return
            seen.add(ident)
            out.append(self.finding(
                sf, node, key=f"{name}:{what}",
                message=(f"traced kernel function '{name}' {why}")))

        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                emit(node, "global",
                     "declares `global` — module-state mutation inside "
                     "a traced function runs once per TRACE, not per "
                     "call, and is a hidden retrace dependency")
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if not cn:
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "item":
                        emit(node, "item()",
                             "calls .item() — a device->host sync "
                             "inside the traced program")
                    continue
                if cn in _BANNED_EXACT or \
                        any(cn.startswith(p) for p in _BANNED_PREFIXES):
                    emit(node, cn,
                         f"calls {cn}() — impure at trace time (the "
                         f"result is baked into the compiled kernel "
                         f"as a constant)")
                elif cn in _HOST_SYNC:
                    emit(node, cn,
                         f"calls {cn}() — host sync belongs in the "
                         f"dispatch/fetch modules, never inside the "
                         f"kernel factory")
                elif cn.endswith(".item"):
                    emit(node, cn,
                         "calls .item() — a device->host sync inside "
                         "the traced program")
                elif isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in module_names \
                        and node.func.attr in ("append", "add", "update",
                                               "pop", "clear", "extend",
                                               "setdefault"):
                    emit(node, f"{cn}",
                         f"mutates module-level state via {cn}() "
                         f"inside a traced function")
        return out

    # ------------------------------------------------------------------
    def _check_stray_syncs(self, sf: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        dup: Dict[str, int] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn in ("jax.block_until_ready", "jax.device_get"):
                    n = dup.get(cn, 0)
                    dup[cn] = n + 1
                    key = cn if n == 0 else f"{cn}#{n + 1}"
                    out.append(self.finding(
                        sf, node, key=key,
                        message=(f"{cn}() outside the dispatch/fetch "
                                 f"modules — the dispatch ring owns "
                                 f"device synchronization")))
        return out
