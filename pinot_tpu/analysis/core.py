"""Analysis framework core: indexer, findings, suppressions, baseline.

Design notes:

  * One :class:`ModuleIndex` is built per run and shared by every
    checker — each source file is read and ``ast.parse``d exactly once
    (the whole tree is ~170 files; a full six-checker run stays well
    under a second, cheap enough for tier-1).
  * A :class:`Finding` carries BOTH a line number (for humans/editors)
    and a line-number-independent ``key`` (for the baseline): keys are
    built from stable names — class, attribute, function, site, knob —
    so an unrelated edit above a finding does not churn the baseline.
  * Suppression is two-layer: inline ``# lint: <code>(<reason>)``
    comments for violations that are correct-by-argument at the site,
    and the committed baseline for pre-existing accepted findings.
    Both REQUIRE a reason; a bare code suppresses nothing.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple


def repo_root() -> str:
    """The checkout root: parent of the installed ``pinot_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "ANALYSIS_BASELINE.json")


#: ``# lint: code(reason)`` — reason is REQUIRED (an unexplained
#: suppression is just a hidden bug); multiple suppressions may share a
#: line: ``# lint: unlocked(ctor only) hang(bounded by caller)``
_SUPPRESS_RE = re.compile(r"#\s*lint:\s*(.+)$")
_SUPPRESS_ITEM_RE = re.compile(r"([a-z]+)\(([^)]+)\)")


@dataclass
class SourceFile:
    """One parsed module: source text, AST, and its suppression map."""

    path: str              # absolute
    relpath: str           # relative to the repo root, '/'-separated
    source: str
    tree: ast.AST
    #: line number -> {code: reason} (codes suppressed on that line)
    suppressions: Dict[int, Dict[str, str]] = field(default_factory=dict)

    @property
    def lines(self) -> List[str]:
        return self.source.splitlines()

    def suppressed(self, line: int, code: str) -> Optional[str]:
        """Reason if ``code`` is suppressed at ``line`` (the flagged
        line itself, or a standalone suppression comment directly
        above), else None."""
        for ln in (line, line - 1):
            reason = self.suppressions.get(ln, {}).get(code)
            if reason:
                return reason
        return None


def _parse_suppressions(source: str) -> Dict[int, Dict[str, str]]:
    out: Dict[int, Dict[str, str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        items = {code: reason.strip()
                 for code, reason in _SUPPRESS_ITEM_RE.findall(m.group(1))
                 if reason.strip()}
        if items:
            out[i] = items
    return out


class ModuleIndex:
    """Parsed view of the tree under ``root`` (the repo checkout).

    Indexes ``pinot_tpu/`` (production), ``tests/`` (the failpoint
    checker proves every site is armed by a test), and the top-level
    ``bench*.py`` drivers (they read config knobs too). Files that fail
    to parse surface as findings from :meth:`parse_errors` rather than
    crashing the run — a syntax error must fail the gate, not the tool.
    """

    SUBDIRS = ("pinot_tpu", "tests")
    TOP_GLOBS = ("bench.py", "bench_cache.py", "bench_extra.py")

    def __init__(self, root: Optional[str] = None,
                 files: Optional[Iterable[str]] = None):
        self.root = os.path.abspath(root or repo_root())
        self._files: Dict[str, SourceFile] = {}
        self._errors: List[Tuple[str, str]] = []
        paths: List[str] = []
        if files is not None:
            paths = [os.path.join(self.root, f) if not os.path.isabs(f)
                     else f for f in files]
        else:
            for sub in self.SUBDIRS:
                base = os.path.join(self.root, sub)
                for dirpath, dirs, names in os.walk(base):
                    dirs[:] = [d for d in dirs if d != "__pycache__"]
                    paths.extend(os.path.join(dirpath, n)
                                 for n in sorted(names)
                                 if n.endswith(".py"))
            for g in self.TOP_GLOBS:
                p = os.path.join(self.root, g)
                if os.path.exists(p):
                    paths.append(p)
        for p in paths:
            rel = os.path.relpath(p, self.root).replace(os.sep, "/")
            try:
                with open(p, encoding="utf-8") as f:
                    src = f.read()
                tree = ast.parse(src, filename=p)
            except (OSError, SyntaxError, ValueError) as e:
                self._errors.append((rel, f"{type(e).__name__}: {e}"))
                continue
            self._files[rel] = SourceFile(
                path=p, relpath=rel, source=src, tree=tree,
                suppressions=_parse_suppressions(src))

    def files(self, prefix: str = "") -> List[SourceFile]:
        return [sf for rel, sf in sorted(self._files.items())
                if rel.startswith(prefix)]

    def get(self, relpath: str) -> Optional[SourceFile]:
        return self._files.get(relpath)

    def parse_errors(self) -> List["Finding"]:
        return [Finding(checker="parse", code="parse", file=rel, line=0,
                        key=rel, message=msg)
                for rel, msg in self._errors]


@dataclass
class Finding:
    checker: str    # registry name, e.g. 'locks'
    code: str       # suppression code, e.g. 'unlocked'
    file: str       # repo-relative path
    line: int
    key: str        # stable, line-independent baseline fingerprint
    message: str
    #: set by run_analysis when the finding is accepted somewhere
    suppressed_by: Optional[str] = None   # 'inline' | 'baseline'
    reason: Optional[str] = None

    def ident(self) -> Tuple[str, str, str]:
        return (self.checker, self.file, self.key)

    def render(self) -> str:
        return (f"{self.file}:{self.line}: [{self.checker}/{self.code}] "
                f"{self.message}  (key: {self.key})")


class Checker:
    """Base class; subclasses register via :func:`register`."""

    name = "base"
    code = "base"

    def run(self, index: ModuleIndex) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    # -- helpers shared by checkers -----------------------------------
    def finding(self, sf: SourceFile, node_or_line, key: str,
                message: str) -> Finding:
        line = (node_or_line if isinstance(node_or_line, int)
                else getattr(node_or_line, "lineno", 0))
        return Finding(checker=self.name, code=self.code, file=sf.relpath,
                       line=line, key=key, message=message)


#: name -> checker instance, populated by @register at import time
CHECKERS: Dict[str, Checker] = {}


def register(cls):
    CHECKERS[cls.name] = cls()
    return cls


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[Tuple[str, str, str], str]:
    """{(checker, file, key): reason}. Entries without a non-empty
    reason are IGNORED (and therefore fail the gate) — the baseline is
    the written-justification ledger, not a mute button."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], str] = {}
    for e in data.get("findings", []):
        reason = str(e.get("reason", "")).strip()
        if not reason:
            continue
        out[(e["checker"], e["file"], e["key"])] = reason
    return out


def write_baseline(path: str, findings: List[Finding],
                   reason: str = "TODO: justify or fix") -> None:
    """Emit a baseline skeleton for the given findings. Meant for
    bootstrapping — every TODO reason must be replaced by hand before
    the entry counts (load_baseline drops empty reasons only, but code
    review owns the TODOs)."""
    entries = [{"checker": f.checker, "file": f.file, "key": f.key,
                "line": f.line, "message": f.message, "reason": reason}
               for f in sorted(findings,
                               key=lambda f: (f.checker, f.file, f.key))]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=1)
        f.write("\n")


@dataclass
class AnalysisReport:
    findings: List[Finding]                 # every raw finding
    unsuppressed: List[Finding]
    inline_suppressed: List[Finding]
    baselined: List[Finding]
    #: baseline entries that matched no current finding — stale entries
    #: are surfaced (fix landed? key drifted?) but do not fail the gate
    stale_baseline: List[Tuple[str, str, str]]

    def to_json(self) -> dict:
        def fd(f: Finding) -> dict:
            d = {"checker": f.checker, "code": f.code, "file": f.file,
                 "line": f.line, "key": f.key, "message": f.message}
            if f.suppressed_by:
                d["suppressed_by"] = f.suppressed_by
                d["reason"] = f.reason
            return d
        return {
            "unsuppressed": [fd(f) for f in self.unsuppressed],
            "inline_suppressed": [fd(f) for f in self.inline_suppressed],
            "baselined": [fd(f) for f in self.baselined],
            "stale_baseline": [list(k) for k in self.stale_baseline],
            "counts": {
                "unsuppressed": len(self.unsuppressed),
                "inline_suppressed": len(self.inline_suppressed),
                "baselined": len(self.baselined),
                "stale_baseline": len(self.stale_baseline),
            },
        }


def run_analysis(index: Optional[ModuleIndex] = None,
                 checkers: Optional[Iterable[str]] = None,
                 baseline: Optional[Dict[Tuple[str, str, str], str]] = None,
                 ) -> AnalysisReport:
    """Run the selected checkers and classify every finding."""
    index = index or ModuleIndex()
    baseline = baseline or {}
    names = list(checkers) if checkers else sorted(CHECKERS)
    findings: List[Finding] = list(index.parse_errors())
    for name in names:
        findings.extend(CHECKERS[name].run(index))

    unsuppressed: List[Finding] = []
    inline_sup: List[Finding] = []
    baselined: List[Finding] = []
    matched_keys = set()
    for f in findings:
        sf = index.get(f.file)
        reason = sf.suppressed(f.line, f.code) if sf is not None else None
        if reason is not None:
            f.suppressed_by, f.reason = "inline", reason
            inline_sup.append(f)
            continue
        breason = baseline.get(f.ident())
        if breason is not None:
            f.suppressed_by, f.reason = "baseline", breason
            matched_keys.add(f.ident())
            baselined.append(f)
            continue
        unsuppressed.append(f)
    stale = sorted(set(baseline) - matched_keys)
    return AnalysisReport(findings=findings, unsuppressed=unsuppressed,
                          inline_suppressed=inline_sup,
                          baselined=baselined, stale_baseline=stale)


# ---------------------------------------------------------------------------
# small AST helpers shared by checkers
# ---------------------------------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``jax.jit`` for jax.jit(...),
    ``fire`` for fire(...); '' when the target is not a name chain."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def kwarg_names(node: ast.Call) -> List[str]:
    return [k.arg for k in node.keywords if k.arg is not None]


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef in the tree."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
