"""Broker role: routing, scatter-gather, reduce, client HTTP API.

Reference parity: pinot-broker (SURVEY.md L8 + §2.7):
BaseSingleStageBrokerRequestHandler.handleRequest (requesthandler/...:280),
BrokerRoutingManager (routing/BrokerRoutingManager.java:100),
TimeBoundaryManager, QueryRouter scatter + BrokerReduceService gather.
"""
