"""Adaptive server selection: route around slow replicas.

Reference parity: pinot-broker
routing/adaptiveserverselector/{LatencySelector, NumInFlightReqSelector,
HybridSelector}.java — the failure detector handles DEAD servers; this
handles SLOW ones by preferring replicas with lower EWMA latency and
fewer in-flight requests (VERDICT r4 missing #7).

Scores are 'lower is better':
  latency   — EWMA of observed request seconds
  inflight  — current outstanding requests
  hybrid    — ewma_latency * (1 + inflight)   (the default)
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Set

from pinot_tpu.utils.metrics import Timer


class AdaptiveServerSelector:
    def __init__(self, mode: str = "hybrid", alpha: float = 0.3):
        assert mode in ("latency", "inflight", "hybrid")
        self.mode = mode
        self.alpha = alpha
        self._ewma: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}
        #: per-server latency RESERVOIRS (utils/metrics.Timer, Vitter R):
        #: every request's latency has equal sampling probability, so the
        #: pooled samples carry the TRUE per-request tail — an EWMA
        #: smooths exactly the spikes a hedge trigger needs to see
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    # -- stats feed (the broker wraps every server request) --------------
    def record_start(self, server: str) -> None:
        with self._lock:
            self._inflight[server] = self._inflight.get(server, 0) + 1

    def record_end(self, server: str, latency_s: float) -> None:
        with self._lock:
            self._inflight[server] = max(
                0, self._inflight.get(server, 0) - 1)
            cur = self._ewma.get(server)
            self._ewma[server] = latency_s if cur is None else \
                (1 - self.alpha) * cur + self.alpha * latency_s
            t = self._timers.get(server)
            if t is None:
                t = self._timers[server] = Timer()
            t.update(latency_s * 1e3)

    def latency_quantile(self, q: float) -> float:
        """Quantile (seconds) over the POOLED per-server latency
        reservoirs — the hedged-scatter trigger delay: a request still
        pending past the fleet's p95 is in the slow tail worth hedging
        ("The Tail at Scale"). Pooled raw samples replace the earlier
        p95-of-EWMA: quantiles of smoothed means understate tails (an
        EWMA never reaches the spikes), so hedges fired either too early
        or, after a calm stretch, far too late. Caveat: reservoirs are
        fixed-size, so the pool weights SERVERS equally, not requests —
        a low-traffic outlier replica is over-represented relative to
        its request share (volume-weighted pooling is a follow-up); the
        tail spikes themselves are still carried faithfully, which is
        what the trigger needs. 0.0 until any latency has been observed
        (callers clamp with the configured floor)."""
        with self._lock:
            vals = sorted(s for t in self._timers.values()
                          for s in t.samples)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, max(0, math.ceil(q * len(vals)) - 1))
        return vals[idx] / 1e3

    # -- selection -------------------------------------------------------
    def score(self, server: str) -> float:
        with self._lock:
            lat = self._ewma.get(server, 0.0)
            inf = self._inflight.get(server, 0)
        if self.mode == "latency":
            return lat
        if self.mode == "inflight":
            return float(inf)
        return lat * (1.0 + inf)

    def pick(self, servers: List[str], skip: Set[str],
             rr: int = 0) -> Optional[str]:
        """Lowest-score healthy replica; rr breaks exact ties so cold
        startup (all scores 0) still round-robins. Scores snapshot ONCE —
        concurrent stat updates must not change them mid-selection."""
        healthy = [s for s in servers if s not in skip]
        if not healthy:
            return None
        snap = {s: self.score(s) for s in healthy}
        best = min(snap.values())
        ties = sorted(s for s, sc in snap.items() if sc == best)
        return ties[rr % len(ties)]
