"""Adaptive server selection + broker self-protection primitives.

Reference parity: pinot-broker
routing/adaptiveserverselector/{LatencySelector, NumInFlightReqSelector,
HybridSelector}.java — the failure detector handles DEAD servers; this
handles SLOW ones by preferring replicas with lower EWMA latency and
fewer in-flight requests (VERDICT r4 missing #7).

Scores are 'lower is better':
  latency   — EWMA of observed request seconds
  inflight  — current outstanding requests
  hybrid    — ewma_latency * (1 + inflight)   (the default)

:class:`RetryBudget` is the broker's anti-amplification governor
(Finagle's RetryBudget shape): retries and hedges are paid for out of a
per-table token bucket that only clean primary responses refill, so a
failing or overloaded fleet sees offered load CONVERGE toward the
organic rate instead of multiplying — the retry-storm failure mode
("The Tail at Scale"; DAGOR, SOSP 2018).
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Set

from pinot_tpu.utils.metrics import Timer


class RetryBudget:
    """Per-table token bucket: every clean primary response DEPOSITS
    ``ratio`` tokens (capped at ``cap``), every retry/hedge attempt
    WITHDRAWS one. A table starts with ``min_tokens`` so a cold broker
    can still salvage the odd failure; a table drowning in failures
    runs dry and its failures surface as typed partials instead of
    re-offered load. Disabled = every withdrawal granted (the pre-PR-15
    behavior, and the bench --overload unprotected A/B leg)."""

    def __init__(self, ratio: float = 0.2, min_tokens: float = 3.0,
                 cap: float = 10.0, enabled: bool = True,
                 metrics=None):
        self.enabled = bool(enabled)
        self.ratio = max(0.0, float(ratio))
        self.min_tokens = max(0.0, float(min_tokens))
        self.cap = max(self.min_tokens, float(cap))
        self._tokens: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._metrics = metrics

    @classmethod
    def from_config(cls, config, metrics=None) -> "RetryBudget":
        if config is None:
            return cls(metrics=metrics)
        return cls(
            ratio=config.get_float("pinot.broker.retry.budget.ratio"),
            min_tokens=config.get_float("pinot.broker.retry.budget.min"),
            cap=config.get_float("pinot.broker.retry.budget.cap"),
            enabled=config.get_bool("pinot.broker.retry.budget.enabled",
                                    True),
            metrics=metrics)

    def _gauge(self, table: str, tokens: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("broker_retry_budget_tokens",
                                    round(tokens, 3),
                                    labels={"table": table})

    def deposit(self, table: str) -> None:
        """One clean primary response earns ``ratio`` retries' worth."""
        if not self.enabled:
            return
        with self._lock:
            cur = self._tokens.get(table, self.min_tokens)
            cur = min(self.cap, cur + self.ratio)
            self._tokens[table] = cur
        self._gauge(table, cur)

    def try_withdraw(self, table: str, cost: float = 1.0) -> bool:
        """Spend one retry/hedge; False = budget exhausted (the caller
        surfaces the failure typed instead of retrying)."""
        if not self.enabled:
            return True
        with self._lock:
            cur = self._tokens.get(table, self.min_tokens)
            if cur < cost:
                granted = False
            else:
                granted = True
                cur -= cost
                self._tokens[table] = cur
        if not granted:
            if self._metrics is not None:
                self._metrics.add_meter("broker_retry_budget_exhausted")
            return False
        self._gauge(table, cur)
        return True

    def tokens(self, table: str) -> float:
        with self._lock:
            return self._tokens.get(table, self.min_tokens)


class AdaptiveServerSelector:
    def __init__(self, mode: str = "hybrid", alpha: float = 0.3):
        assert mode in ("latency", "inflight", "hybrid")
        self.mode = mode
        self.alpha = alpha
        self._ewma: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}
        #: per-server latency RESERVOIRS (utils/metrics.Timer, Vitter R):
        #: every request's latency has equal sampling probability, so the
        #: pooled samples carry the TRUE per-request tail — an EWMA
        #: smooths exactly the spikes a hedge trigger needs to see
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    # -- stats feed (the broker wraps every server request) --------------
    def record_start(self, server: str) -> None:
        with self._lock:
            self._inflight[server] = self._inflight.get(server, 0) + 1

    def record_end(self, server: str, latency_s: float) -> None:
        with self._lock:
            self._inflight[server] = max(
                0, self._inflight.get(server, 0) - 1)
            cur = self._ewma.get(server)
            self._ewma[server] = latency_s if cur is None else \
                (1 - self.alpha) * cur + self.alpha * latency_s
            t = self._timers.get(server)
            if t is None:
                t = self._timers[server] = Timer()
            t.update(latency_s * 1e3)

    def latency_quantile(self, q: float) -> float:
        """Quantile (seconds) over the POOLED per-server latency
        reservoirs — the hedged-scatter trigger delay: a request still
        pending past the fleet's p95 is in the slow tail worth hedging
        ("The Tail at Scale"). Pooled raw samples replace the earlier
        p95-of-EWMA: quantiles of smoothed means understate tails (an
        EWMA never reaches the spikes), so hedges fired either too early
        or, after a calm stretch, far too late. Caveat: reservoirs are
        fixed-size, so the pool weights SERVERS equally, not requests —
        a low-traffic outlier replica is over-represented relative to
        its request share (volume-weighted pooling is a follow-up); the
        tail spikes themselves are still carried faithfully, which is
        what the trigger needs. 0.0 until any latency has been observed
        (callers clamp with the configured floor)."""
        with self._lock:
            vals = sorted(s for t in self._timers.values()
                          for s in t.samples)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, max(0, math.ceil(q * len(vals)) - 1))
        return vals[idx] / 1e3

    # -- selection -------------------------------------------------------
    def score(self, server: str) -> float:
        with self._lock:
            lat = self._ewma.get(server, 0.0)
            inf = self._inflight.get(server, 0)
        if self.mode == "latency":
            return lat
        if self.mode == "inflight":
            return float(inf)
        return lat * (1.0 + inf)

    def pick(self, servers: List[str], skip: Set[str],
             rr: int = 0) -> Optional[str]:
        """Lowest-score healthy replica; rr breaks exact ties so cold
        startup (all scores 0) still round-robins. Scores snapshot ONCE —
        concurrent stat updates must not change them mid-selection."""
        healthy = [s for s in servers if s not in skip]
        if not healthy:
            return None
        snap = {s: self.score(s) for s in healthy}
        best = min(snap.values())
        ties = sorted(s for s, sc in snap.items() if sc == best)
        return ties[rr % len(ties)]
