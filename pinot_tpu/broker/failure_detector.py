"""Broker-side server failure detection with exponential-backoff retry.

Reference parity: pinot-broker
failuredetector/ConnectionFailureDetector.java (+ BaseExponentialBackoff
RetryFailureDetector) — servers that fail a query connection are marked
unhealthy and routing skips them; after an exponentially growing backoff
the server re-enters routing as a probe, and one success clears it.

Three evidence classes, in decreasing severity:

* **failures** (connection refused/reset) — the server may be dead:
  full exponential escalation up to ``max_backoff_s``.
* **timeouts** (deadline miss) — the server is slow, not dead: capped
  exponential with jitter, so repeated misses cool the replica
  progressively but a single miss costs one base interval, and
  same-instant marks from N gather threads don't re-probe in lockstep.
* **overloads** (typed 211 admission rejection) — the server is ALIVE
  and explicitly asking for less load: the lightest weight (half a
  timeout per mark, backoff ceiling a quarter of the timeout ceiling,
  the server's own retryAfterMs hint respected when longer), so a
  briefly-saturated replica re-enters routing long before a dead one.
  Overload marks additionally record an ``overloaded-until`` horizon
  the request handler reads to auto-disable hedging fleet-wide.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional, Set


class _Entry:
    __slots__ = ("failures", "retry_at", "slow", "overload_until")

    def __init__(self):
        self.failures = 0
        self.retry_at = 0.0
        #: slowness evidence: +1.0 per deadline miss, +0.5 per overload
        #: rejection — the exponent of the capped-exponential backoff
        self.slow = 0.0
        #: horizon until which this server is considered overloaded
        #: (hedging auto-disables while any server is past now here)
        self.overload_until = 0.0


class ConnectionFailureDetector:
    def __init__(self, base_backoff_s: float = 1.0,
                 max_backoff_s: float = 60.0,
                 jitter_seed: Optional[int] = None):
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        #: private PRNG: backoff jitter must not perturb (or depend on)
        #: the global random state; seedable so tests are exact
        self._rng = random.Random(jitter_seed)

    # ------------------------------------------------------------------
    def _entry_locked(self, server: str) -> _Entry:
        e = self._entries.get(server)
        if e is None:
            e = self._entries[server] = _Entry()
        return e

    def mark_failure(self, server: str) -> None:
        with self._lock:
            e = self._entry_locked(server)
            e.failures += 1
            backoff = min(self.base_backoff_s * (2 ** (e.failures - 1)),
                          self.max_backoff_s)
            e.retry_at = time.time() + backoff

    def mark_timeout(self, server: str) -> None:
        """A deadline miss is evidence of SLOWNESS, not death: capped
        exponential with jitter — one miss costs about one base
        interval, repeated misses escalate toward the ceiling, and the
        jitter factor (uniform [0.5, 1.0]) staggers re-probes so N
        queries that all expired on the same slow replica don't hammer
        it again in the same instant. No failure-count growth: a
        recovered server is one clean response from full health."""
        with self._lock:
            e = self._entry_locked(server)
            e.slow += 1.0
            backoff = min(self.base_backoff_s * (2 ** (e.slow - 1)),
                          self.max_backoff_s)
            backoff *= 0.5 + 0.5 * self._rng.random()
            e.retry_at = max(e.retry_at, time.time() + backoff)

    def mark_overload(self, server: str,
                      retry_after_s: Optional[float] = None) -> None:
        """A typed 211 admission rejection: the server is alive and
        shedding. Half the evidence weight of a timeout and a quarter
        of its backoff ceiling, so a briefly-saturated replica is never
        exiled as long as a dead one; the server's own retryAfterMs
        hint wins when it asks for longer."""
        with self._lock:
            e = self._entry_locked(server)
            e.slow += 0.5
            backoff = min(self.base_backoff_s * (2 ** (e.slow - 1)),
                          self.max_backoff_s / 4.0)
            backoff *= 0.5 + 0.5 * self._rng.random()
            if retry_after_s is not None:
                backoff = max(backoff, min(float(retry_after_s),
                                           self.max_backoff_s / 4.0))
            now = time.time()
            e.retry_at = max(e.retry_at, now + backoff)
            e.overload_until = max(e.overload_until, now + backoff)

    def mark_success(self, server: str) -> None:
        with self._lock:
            self._entries.pop(server, None)

    # ------------------------------------------------------------------
    def is_healthy(self, server: str, now: Optional[float] = None) -> bool:
        """True when routable: never failed, or its backoff expired (the
        next request is the re-probe; a failure re-doubles the backoff)."""
        now = time.time() if now is None else now
        with self._lock:
            e = self._entries.get(server)
            return e is None or now >= e.retry_at

    def unhealthy_servers(self, now: Optional[float] = None) -> Set[str]:
        now = time.time() if now is None else now
        with self._lock:
            return {s for s, e in self._entries.items() if now < e.retry_at}

    def overloaded_servers(self, now: Optional[float] = None) -> Set[str]:
        now = time.time() if now is None else now
        with self._lock:
            return {s for s, e in self._entries.items()
                    if now < e.overload_until}

    def any_overloaded(self, now: Optional[float] = None) -> bool:
        """True while any server's overload horizon is in the future —
        the hedging auto-disable signal: speculative duplicate load is
        exactly the wrong medicine for a fleet already shedding."""
        now = time.time() if now is None else now
        with self._lock:
            return any(now < e.overload_until
                       for e in self._entries.values())

    def failure_count(self, server: str) -> int:
        with self._lock:
            e = self._entries.get(server)
            return 0 if e is None else e.failures
