"""Broker-side server failure detection with exponential-backoff retry.

Reference parity: pinot-broker
failuredetector/ConnectionFailureDetector.java (+ BaseExponentialBackoff
RetryFailureDetector) — servers that fail a query connection are marked
unhealthy and routing skips them; after an exponentially growing backoff
the server re-enters routing as a probe, and one success clears it.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Set


class _Entry:
    __slots__ = ("failures", "retry_at")

    def __init__(self):
        self.failures = 0
        self.retry_at = 0.0


class ConnectionFailureDetector:
    def __init__(self, base_backoff_s: float = 1.0,
                 max_backoff_s: float = 60.0):
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def mark_failure(self, server: str) -> None:
        with self._lock:
            e = self._entries.get(server)
            if e is None:
                e = self._entries[server] = _Entry()
            e.failures += 1
            backoff = min(self.base_backoff_s * (2 ** (e.failures - 1)),
                          self.max_backoff_s)
            e.retry_at = time.time() + backoff

    def mark_timeout(self, server: str) -> None:
        """A deadline miss is evidence of SLOWNESS, not death: apply one
        flat base backoff so the next few queries prefer other replicas,
        without the exponential escalation (or failure-count growth)
        reserved for hard connection failures — a recovered server
        re-enters routing after a single interval."""
        with self._lock:
            e = self._entries.get(server)
            if e is None:
                e = self._entries[server] = _Entry()
            e.retry_at = max(e.retry_at, time.time() + self.base_backoff_s)

    def mark_success(self, server: str) -> None:
        with self._lock:
            self._entries.pop(server, None)

    # ------------------------------------------------------------------
    def is_healthy(self, server: str, now: Optional[float] = None) -> bool:
        """True when routable: never failed, or its backoff expired (the
        next request is the re-probe; a failure re-doubles the backoff)."""
        now = time.time() if now is None else now
        with self._lock:
            e = self._entries.get(server)
            return e is None or now >= e.retry_at

    def unhealthy_servers(self, now: Optional[float] = None) -> Set[str]:
        now = time.time() if now is None else now
        with self._lock:
            return {s for s, e in self._entries.items() if now < e.retry_at}

    def failure_count(self, server: str) -> int:
        with self._lock:
            e = self._entries.get(server)
            return 0 if e is None else e.failures
