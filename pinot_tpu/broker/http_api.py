"""Broker HTTP API: the client edge.

Reference parity: pinot-broker api/resources/PinotClientRequest.java:100 —
POST /query/sql with JSON {"sql": "..."} returning the BrokerResponse
JSON. GET /health for liveness. Stdlib http.server on a daemon thread (no
web framework in the image; the broker edge is not the hot path).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from pinot_tpu.broker.request_handler import BrokerRequestHandler


class BrokerHttpServer:
    def __init__(self, handler: BrokerRequestHandler, host: str = "127.0.0.1",
                 port: int = 0):
        self.handler = handler
        broker = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                path = self.path.partition("?")[0].rstrip("/") or "/"
                if path == "/health":
                    body = b"OK"
                elif path == "/metrics":
                    from pinot_tpu.utils.metrics import get_registry
                    body = get_registry("broker").prometheus_text().encode() \
                        + get_registry("server").prometheus_text().encode()
                elif path.startswith("/debug/"):
                    # /debug/traces[/<id>] + /debug/queries: the broker's
                    # trace store + in-flight registry (trace_store.py)
                    from pinot_tpu.utils.trace_store import debug_payload
                    payload = debug_payload("broker", path)
                    if payload is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    body = json.dumps(payload, default=str).encode()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path not in ("/query/sql", "/query"):
                    self.send_response(404)
                    self.end_headers()
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n))
                    sql = req["sql"]
                    if not isinstance(sql, str):
                        raise TypeError("sql must be a string")
                except (json.JSONDecodeError, KeyError, TypeError):
                    self.send_response(400)
                    self.end_headers()
                    return
                resp = broker.handler.handle(sql)
                body = json.dumps(resp.to_dict(), default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"broker-http-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
