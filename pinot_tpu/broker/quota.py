"""Per-table and per-tenant query quotas: token-bucket QPS limits.

Reference parity: pinot-broker
queryquota/HelixExternalViewBasedQueryQuotaManager.java — per-table
maxQueriesPerSecond from TableConfig, enforced broker-side with a rate
limiter; exceeding it rejects the query (the reference meters and
answers 429-equivalent errors) instead of letting a runaway tenant
starve the cluster (VERDICT r4 missing #7). Layered on top: per-TENANT
buckets (the table->tenant map comes from TableConfig tenant tags), so
one tenant's whole table fleet shares a ceiling — a noisy tenant's
flood is rejected at the broker edge before it can crowd the scatter
pool, and the rejection names the tenant, not an innocent table.

Acquisition is all-or-nothing across both scopes: a query consumes a
table token AND a tenant token only when BOTH buckets have one —
otherwise a rejected query would still drain the surviving scope's
budget and the 429s would cascade onto well-behaved tables.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class _Bucket:
    def __init__(self, qps: float):
        self.qps = qps
        #: burst capacity >= 1 so fractional quotas (0.5 QPS = one query
        #: per 2s) still admit queries instead of rejecting forever
        self.cap = max(qps, 1.0)
        self.tokens = self.cap
        self.last = time.monotonic()

    def refill(self) -> None:
        now = time.monotonic()
        self.tokens = min(self.cap, self.tokens + (now - self.last) * self.qps)
        self.last = now

    def has_token(self) -> bool:
        return self.tokens >= 1.0

    def take(self) -> None:
        self.tokens -= 1.0

    def try_acquire(self) -> bool:
        self.refill()
        if self.has_token():
            self.take()
            return True
        return False


class QueryQuotaManager:
    def __init__(self):
        self._buckets: Dict[str, _Bucket] = {}
        self._tenant_buckets: Dict[str, _Bucket] = {}
        self._table_tenant: Dict[str, str] = {}
        self._lock = threading.Lock()

    # -- configuration -------------------------------------------------
    def set_quota(self, table: str, qps: Optional[float]) -> None:
        """qps None/<=0 removes the limit."""
        with self._lock:
            self._set(self._buckets, table, qps)

    def set_tenant_quota(self, tenant: str, qps: Optional[float]) -> None:
        """Cluster-wide QPS ceiling for one tenant's whole table fleet."""
        with self._lock:
            self._set(self._tenant_buckets, tenant, qps)

    @staticmethod
    def _set(buckets: Dict[str, _Bucket], key: str,
             qps: Optional[float]) -> None:
        if qps is None or qps <= 0:
            buckets.pop(key, None)
        else:
            cur = buckets.get(key)
            if cur is None or cur.qps != qps:
                buckets[key] = _Bucket(qps)

    def set_table_tenant(self, table: str, tenant: Optional[str]) -> None:
        """Record which tenant's bucket a table's queries draw from."""
        with self._lock:
            if tenant:
                self._table_tenant[table] = tenant
            else:
                self._table_tenant.pop(table, None)

    # -- enforcement ---------------------------------------------------
    def check(self, table: str) -> Optional[str]:
        """None when admitted (tokens consumed); otherwise the rejection
        reason — naming the scope that is actually over budget."""
        return self.check_many([table])

    def check_many(self, tables) -> Optional[str]:
        """All-or-nothing admission for a query reading SEVERAL tables
        (the MSE tree): every table bucket and each DISTINCT tenant
        bucket is charged exactly once, and only when all of them have
        budget — a rejection must not drain any scope, and one N-table
        query is one query against its tenant's ceiling."""
        with self._lock:
            table_buckets = []
            tenant_buckets = {}
            for table in dict.fromkeys(tables):  # dedup, order kept
                tb = self._buckets.get(table)
                if tb is not None:
                    tb.refill()
                    table_buckets.append((table, tb))
                tenant = self._table_tenant.get(table)
                if tenant and tenant not in tenant_buckets:
                    nb = self._tenant_buckets.get(tenant)
                    if nb is not None:
                        nb.refill()
                        tenant_buckets[tenant] = nb
            for table, tb in table_buckets:
                if not tb.has_token():
                    return f"table {table} is over its QPS quota"
            for tenant, nb in tenant_buckets.items():
                if not nb.has_token():
                    return f"tenant {tenant} is over its QPS quota"
            # every scope has budget: consume atomically
            for _table, tb in table_buckets:
                tb.take()
            for nb in tenant_buckets.values():
                nb.take()
            return None

    def try_acquire(self, table: str) -> bool:
        """False when the table (or its tenant) is over its QPS quota."""
        return self.check(table) is None

    # -- introspection -------------------------------------------------
    def quota_of(self, table: str) -> Optional[float]:
        with self._lock:
            b = self._buckets.get(table)
            return b.qps if b else None

    def tenant_quota_of(self, tenant: str) -> Optional[float]:
        with self._lock:
            b = self._tenant_buckets.get(tenant)
            return b.qps if b else None

    def tenant_of(self, table: str) -> Optional[str]:
        with self._lock:
            return self._table_tenant.get(table)
