"""Per-table query quotas: token-bucket QPS limits at the broker.

Reference parity: pinot-broker
queryquota/HelixExternalViewBasedQueryQuotaManager.java — per-table
maxQueriesPerSecond from TableConfig, enforced broker-side with a rate
limiter; exceeding it rejects the query (the reference meters and
answers 429-equivalent errors) instead of letting a runaway tenant
starve the cluster (VERDICT r4 missing #7).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class _Bucket:
    def __init__(self, qps: float):
        self.qps = qps
        #: burst capacity >= 1 so fractional quotas (0.5 QPS = one query
        #: per 2s) still admit queries instead of rejecting forever
        self.cap = max(qps, 1.0)
        self.tokens = self.cap
        self.last = time.monotonic()

    def try_acquire(self) -> bool:
        now = time.monotonic()
        self.tokens = min(self.cap, self.tokens + (now - self.last) * self.qps)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class QueryQuotaManager:
    def __init__(self):
        self._buckets: Dict[str, _Bucket] = {}
        self._lock = threading.Lock()

    def set_quota(self, table: str, qps: Optional[float]) -> None:
        """qps None/<=0 removes the limit."""
        with self._lock:
            if qps is None or qps <= 0:
                self._buckets.pop(table, None)
            else:
                cur = self._buckets.get(table)
                if cur is None or cur.qps != qps:
                    self._buckets[table] = _Bucket(qps)

    def try_acquire(self, table: str) -> bool:
        """False when the table is over its QPS quota."""
        with self._lock:
            b = self._buckets.get(table)
            if b is None:
                return True
            return b.try_acquire()

    def quota_of(self, table: str) -> Optional[float]:
        with self._lock:
            b = self._buckets.get(table)
            return b.qps if b else None
