"""Broker request handling: parse -> route -> scatter -> gather -> reduce.

Reference parity: pinot-broker requesthandler/
BaseSingleStageBrokerRequestHandler.java:280 (compile, authorize, route,
submit) + core/transport/QueryRouter.java:90 (scatter) +
core/query/reduce/BrokerReduceService.java:61 (gather/merge).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Function
from pinot_tpu.query.parser import SqlParseError, parse_sql
from pinot_tpu.query.reduce import BrokerResponse, reduce_results
from pinot_tpu.server import datatable
from pinot_tpu.server.query_server import ServerConnection
from pinot_tpu.broker.routing import BrokerRoutingManager


class BrokerRequestHandler:
    def __init__(self, routing: BrokerRoutingManager,
                 connections: Dict[str, ServerConnection],
                 max_fanout_threads: int = 16,
                 mse_dispatcher=None, failure_detector=None):
        self.routing = routing
        self.connections = connections
        #: multi-stage dispatcher (mse/dispatcher.py); when set, queries the
        #: single-stage grammar rejects (joins, subqueries) — or that opt in
        #: via useMultistageEngine — go through it (ref
        #: BrokerRequestHandlerDelegate engine selection)
        self.mse_dispatcher = mse_dispatcher
        if failure_detector is None:
            from pinot_tpu.broker.failure_detector import \
                ConnectionFailureDetector
            failure_detector = ConnectionFailureDetector()
        self.failure_detector = failure_detector
        self._pool = ThreadPoolExecutor(max_workers=max_fanout_threads)
        self._request_id = 0
        self._lock = threading.Lock()

    def _next_id(self) -> int:
        with self._lock:
            self._request_id += 1
            return self._request_id

    def handle(self, sql: str) -> BrokerResponse:
        start = time.time()
        try:
            query = parse_sql(sql)
            ctx = QueryContext.from_query(query)
        except (SqlParseError, ValueError) as e:
            if self.mse_dispatcher is not None:
                # delegate only if the multi-stage grammar accepts the query
                # (joins/subqueries); a genuine syntax error stays a 150
                try:
                    from pinot_tpu.mse.sql import parse_mse_sql
                    parsed = parse_mse_sql(sql)
                except (SqlParseError, ValueError):
                    return _error_response(
                        150, f"SQLParsingError: {e}", start)
                return self.mse_dispatcher.submit(sql, parsed)
            return _error_response(150, f"SQLParsingError: {e}", start)
        if self.mse_dispatcher is not None and \
                query.options.get("useMultistageEngine", "").lower() == "true":
            return self.mse_dispatcher.submit(sql)
        route = self.routing.get_route(ctx.table)
        if route is None:
            return _error_response(
                190, f"TableDoesNotExistError: {ctx.table}", start)

        plan = route.route(ctx, unhealthy=self.failure_detector
                           .unhealthy_servers())
        request_id = self._next_id()
        results, exceptions, server_stats = [], [], []
        responded = 0
        attempted: set = set()
        failed_servers: set = set()

        def submit(entries):
            out = []
            for server, physical_table, segment_names, extra_filter in entries:
                attempted.add(server)
                conn = self.connections.get(server)
                if conn is None:
                    # a silently skipped server would return a clean-looking
                    # partial aggregate; surface it as a server error
                    exceptions.append(
                        {"errorCode": 427,
                         "message": f"ServerNotConnected: {server}"})
                    continue
                # the time-boundary predicate travels as a separate field,
                # ANDed into the filter TREE server-side — splicing SQL
                # text is unsound (keywords inside identifiers/literals)
                out.append((self._pool.submit(
                    conn.request, physical_table, sql, segment_names,
                    request_id, extra_filter),
                    server, physical_table, segment_names, extra_filter))
            return out

        def gather(entries, retried: bool):
            nonlocal responded
            failed = []
            for fut, server, table, names, extra in entries:
                try:
                    payload = fut.result(timeout=60)
                    server_results, server_exc, stats_extra = \
                        datatable.deserialize_results(payload)
                    results.extend(server_results)
                    exceptions.extend(server_exc)
                    if stats_extra is not None:
                        server_stats.append(stats_extra)
                    responded += 1
                    self.failure_detector.mark_success(server)
                except Exception as e:  # noqa: BLE001 — partial results
                    # connection-level failure: mark unhealthy (routing
                    # skips it until the backoff expires, ref
                    # ConnectionFailureDetector) and retry the segments on
                    # surviving replicas ONCE
                    self.failure_detector.mark_failure(server)
                    failed_servers.add(server)
                    if retried:
                        exceptions.append({"errorCode": 427,
                                           "message": f"ServerError: {e}"})
                        continue
                    # exclude everything known-bad: this round's failures
                    # AND the detector's unhealthy set, or the single
                    # retry can land on another dead server while a
                    # healthy replica exists
                    exclude = failed_servers | \
                        self.failure_detector.unhealthy_servers()
                    rerouted, unplaced = route.reroute_segments(
                        table, names, exclude=exclude, extra_filter=extra)
                    if unplaced:
                        # segments with no surviving replica: surface the
                        # loss instead of a clean-looking partial answer
                        exceptions.append({
                            "errorCode": 427,
                            "message": (f"ServerError: {e} "
                                        f"(segments lost: {unplaced})")})
                    failed.extend(rerouted)
            return failed

        retry_plan = gather(submit(plan), retried=False)
        if retry_plan:
            gather(submit(retry_plan), retried=True)

        resp = reduce_results(ctx, results)
        for extra in server_stats:
            resp.stats.merge(extra)
        resp.exceptions = exceptions
        resp.num_servers_queried = len(attempted)
        resp.num_servers_responded = responded
        resp.time_used_ms = (time.time() - start) * 1000.0
        return resp


def _error_response(code: int, message: str, start: float) -> BrokerResponse:
    resp = BrokerResponse()
    resp.exceptions = [{"errorCode": code, "message": message}]
    resp.time_used_ms = (time.time() - start) * 1000.0
    return resp
