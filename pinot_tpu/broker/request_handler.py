"""Broker request handling: parse -> route -> scatter -> gather -> reduce.

Reference parity: pinot-broker requesthandler/
BaseSingleStageBrokerRequestHandler.java:280 (compile, authorize, route,
submit) + core/transport/QueryRouter.java:90 (scatter) +
core/query/reduce/BrokerReduceService.java:61 (gather/merge).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Function
from pinot_tpu.query.parser import SqlParseError, parse_sql
from pinot_tpu.query.reduce import BrokerResponse, reduce_results
from pinot_tpu.server import datatable
from pinot_tpu.server.query_server import ServerConnection
from pinot_tpu.broker.routing import BrokerRoutingManager


class BrokerRequestHandler:
    def __init__(self, routing: BrokerRoutingManager,
                 connections: Dict[str, ServerConnection],
                 max_fanout_threads: int = 16,
                 mse_dispatcher=None, failure_detector=None,
                 quota_manager=None, config=None, result_cache=None):
        self.routing = routing
        self.connections = connections
        self.config = config
        #: tier-1 whole-result cache (cache/broker_cache.py). Off unless a
        #: config enables pinot.broker.result.cache.enabled or a built
        #: cache is injected — failover semantics (a repeated query must
        #: re-exercise dead servers) are opt-out, not silently cached away.
        if result_cache is None and config is not None:
            from pinot_tpu.cache.broker_cache import BrokerResultCache
            from pinot_tpu.utils.metrics import get_registry
            result_cache = BrokerResultCache.from_config(
                config, metrics=get_registry("broker"))
        self.result_cache = result_cache
        #: per-table QPS limits (ref queryquota/; None = no quotas)
        self.quota_manager = quota_manager
        #: adaptive selector stats feed (routing.selector, may be None)
        self._selector = getattr(routing, "selector", None)
        #: multi-stage dispatcher (mse/dispatcher.py); when set, queries the
        #: single-stage grammar rejects (joins, subqueries) — or that opt in
        #: via useMultistageEngine — go through it (ref
        #: BrokerRequestHandlerDelegate engine selection)
        self.mse_dispatcher = mse_dispatcher
        if failure_detector is None:
            from pinot_tpu.broker.failure_detector import \
                ConnectionFailureDetector
            failure_detector = ConnectionFailureDetector()
        self.failure_detector = failure_detector
        self._pool = ThreadPoolExecutor(max_workers=max_fanout_threads)
        self._request_id = 0
        self._lock = threading.Lock()

    def _next_id(self) -> int:
        with self._lock:
            self._request_id += 1
            return self._request_id

    def _hybrid_offline_enabled(self) -> bool:
        """Hybrid offline-partial caching rides the result cache; the
        knob exists to switch the behavior off independently."""
        if self.config is not None:
            return self.config.get_bool(
                "pinot.broker.result.cache.hybrid.offline", True)
        return True

    def _check_quota(self, table: str) -> bool:
        """QPS quota on the LOGICAL name — quotas register unsuffixed, so
        a _OFFLINE/_REALTIME-suffixed query must hit the same bucket
        (ref HelixExternalViewBasedQueryQuotaManager: over-quota queries
        are rejected, not queued)."""
        if self.quota_manager is None:
            return True
        base = table
        for suffix in ("_OFFLINE", "_REALTIME"):
            if base.endswith(suffix):
                base = base[: -len(suffix)]
        return self.quota_manager.try_acquire(base)

    def _timed_request(self, conn, server, physical_table, sql,
                       segment_names, request_id, extra_filter):
        """conn.request wrapped with adaptive-selector stats (latency +
        in-flight, ref adaptiveserverselector's ServerRoutingStats)."""
        sel = self._selector
        if sel is None:
            return conn.request(physical_table, sql, segment_names,
                                request_id, extra_filter)
        sel.record_start(server)
        t0 = time.time()
        try:
            return conn.request(physical_table, sql, segment_names,
                                request_id, extra_filter)
        finally:
            sel.record_end(server, time.time() - t0)

    def handle(self, sql: str) -> BrokerResponse:
        start = time.time()
        try:
            query = parse_sql(sql)
            ctx = QueryContext.from_query(query)
        except (SqlParseError, ValueError) as e:
            if self.mse_dispatcher is not None:
                # delegate only if the multi-stage grammar accepts the query
                # (joins/subqueries); a genuine syntax error stays a 150
                try:
                    from pinot_tpu.mse.sql import parse_mse_sql
                    parsed = parse_mse_sql(sql)
                except (SqlParseError, ValueError):
                    return _error_response(
                        150, f"SQLParsingError: {e}", start)
                # MSE queries are NOT a quota bypass: meter EVERY table
                # the tree reads (set operands + subquery roots included)
                for t in _mse_tables(parsed):
                    if not self._check_quota(t):
                        return _error_response(
                            429, f"QuotaExceededError: table {t} is over "
                                 f"its QPS quota", start)
                return self.mse_dispatcher.submit(sql, parsed)
            return _error_response(150, f"SQLParsingError: {e}", start)
        if not self._check_quota(ctx.table):
            return _error_response(
                429, f"QuotaExceededError: table {ctx.table} is over its "
                     f"QPS quota", start)
        if self.mse_dispatcher is not None and \
                query.options.get("useMultistageEngine", "").lower() == "true":
            return self.mse_dispatcher.submit(sql)
        route = self.routing.get_route(ctx.table)
        if route is None:
            return _error_response(
                190, f"TableDoesNotExistError: {ctx.table}", start)

        # -- tier-1 whole-result cache ---------------------------------
        # keyed by (query fingerprint, table, routing epoch): the epoch
        # hashes the segment set + versions, so segment add/replace/remove
        # invalidates by construction. Tables with consuming segments are
        # skipped unless cache_realtime — appends don't move the epoch.
        cache_key = None
        offline_key = None  # hybrid offline-partial cache key
        cacheable = False
        if self.result_cache is not None and self.result_cache.enabled \
                and not ctx.explain \
                and ctx.options.get("trace", "").lower() != "true":
            from pinot_tpu.cache.broker_cache import cache_bypassed
            cacheable = not cache_bypassed(ctx.options)
            if cacheable and (self.result_cache.cache_realtime
                              or not route.has_realtime):
                epoch = route.epoch()
                if not epoch.startswith("<torn:"):
                    # a torn epoch never repeats: a get can't hit and a
                    # put would leak an unaddressable entry — skip both
                    cache_key = (ctx.fingerprint(), ctx.table, epoch)
                    hit = self.result_cache.get(*cache_key)
                    if hit is not None:
                        hit.cache_hit = True
                        hit.time_used_ms = (time.time() - start) * 1000.0
                        return hit

        plan = route.route(ctx, unhealthy=self.failure_detector
                           .unhealthy_servers())
        request_id = self._next_id()
        results, exceptions, server_stats = [], [], []
        responded = 0
        attempted: set = set()
        failed_servers: set = set()

        # -- hybrid-table offline-partial cache ------------------------
        # when the whole result is uncacheable because of a consuming
        # side, the OFFLINE side's merged partial still is: keyed by the
        # offline epoch, so only the realtime entries re-scatter. The
        # partial is the raw per-server result list — reduce merges it
        # with the realtime side's fresh results exactly as if the
        # offline servers had answered.
        offline_results: list = []
        offline_stats: list = []
        offline_failed = [False]
        if cacheable and cache_key is None \
                and route.offline is not None and route.has_realtime \
                and self._hybrid_offline_enabled():
            off_epoch = route.offline_epoch()
            if not off_epoch.startswith("<torn:"):
                key = (ctx.fingerprint(), ctx.table, off_epoch)
                # READ whenever the epoch is clean: stored partials are
                # complete by construction (see the PUT gate), so during
                # an offline-server outage the cache is strictly better
                # than the degraded scatter routing would attempt
                cached = self.result_cache.get_offline_partial(*key)
                if cached is not None:
                    cached_results, cached_stats = cached
                    results.extend(cached_results)
                    if cached_stats is not None:
                        server_stats.append(cached_stats)
                    plan = [e for e in plan
                            if not e[1].endswith("_OFFLINE")]
                else:
                    # PUT only when the plan covers every unpruned
                    # offline segment: a segment with no placeable
                    # replica is silently dropped from the plan (routing
                    # tolerates it; the query degrades), but the epoch
                    # hashes the segment SET, not placement — a partial
                    # missing those rows would be served as complete
                    # until TTL
                    planned_off = {n for _srv, tbl, names, _ef in plan
                                   if tbl.endswith("_OFFLINE")
                                   for n in names}
                    if planned_off == route.offline_segments_for(ctx):
                        offline_key = key

        def submit(entries):
            out = []
            for server, physical_table, segment_names, extra_filter in entries:
                attempted.add(server)
                conn = self.connections.get(server)
                if conn is None:
                    # a silently skipped server would return a clean-looking
                    # partial aggregate; surface it as a server error
                    exceptions.append(
                        {"errorCode": 427,
                         "message": f"ServerNotConnected: {server}"})
                    if physical_table.endswith("_OFFLINE"):
                        offline_failed[0] = True
                    continue
                # the time-boundary predicate travels as a separate field,
                # ANDed into the filter TREE server-side — splicing SQL
                # text is unsound (keywords inside identifiers/literals)
                out.append((self._pool.submit(
                    self._timed_request, conn, server, physical_table, sql,
                    segment_names, request_id, extra_filter),
                    server, physical_table, segment_names, extra_filter))
            return out

        def gather(entries, retried: bool):
            nonlocal responded
            failed = []
            for fut, server, table, names, extra in entries:
                try:
                    payload = fut.result(timeout=60)
                    server_results, server_exc, stats_extra = \
                        datatable.deserialize_results(payload)
                    results.extend(server_results)
                    if table.endswith("_OFFLINE"):
                        if server_exc:
                            offline_failed[0] = True
                        else:
                            offline_results.extend(server_results)
                            if stats_extra is not None:
                                offline_stats.append(stats_extra)
                    exceptions.extend(server_exc)
                    if stats_extra is not None:
                        server_stats.append(stats_extra)
                    responded += 1
                    self.failure_detector.mark_success(server)
                except Exception as e:  # noqa: BLE001 — partial results
                    # connection-level failure: mark unhealthy (routing
                    # skips it until the backoff expires, ref
                    # ConnectionFailureDetector) and retry the segments on
                    # surviving replicas ONCE
                    if table.endswith("_OFFLINE"):
                        offline_failed[0] = True
                    self.failure_detector.mark_failure(server)
                    failed_servers.add(server)
                    if retried:
                        exceptions.append({"errorCode": 427,
                                           "message": f"ServerError: {e}"})
                        continue
                    # exclude everything known-bad: this round's failures
                    # AND the detector's unhealthy set, or the single
                    # retry can land on another dead server while a
                    # healthy replica exists
                    exclude = failed_servers | \
                        self.failure_detector.unhealthy_servers()
                    rerouted, unplaced = route.reroute_segments(
                        table, names, exclude=exclude, extra_filter=extra)
                    if unplaced:
                        # segments with no surviving replica: surface the
                        # loss instead of a clean-looking partial answer
                        exceptions.append({
                            "errorCode": 427,
                            "message": (f"ServerError: {e} "
                                        f"(segments lost: {unplaced})")})
                    failed.extend(rerouted)
            return failed

        retry_plan = gather(submit(plan), retried=False)
        if retry_plan:
            gather(submit(retry_plan), retried=True)

        if offline_key is not None and offline_results \
                and not offline_failed[0]:
            # complete, clean offline side: reusable until the offline
            # epoch moves (a retry-salvaged round is conservatively NOT
            # cached — offline_failed stays set once any entry failed).
            # Server-level stats ride along so a cache-served response
            # reports the same pruning counts as an uncached run.
            merged_stats = None
            if offline_stats:
                from pinot_tpu.query.results import ExecutionStats
                merged_stats = ExecutionStats()
                for s in offline_stats:
                    merged_stats.merge(s)
            self.result_cache.put_offline_partial(*offline_key,
                                                  offline_results,
                                                  stats=merged_stats)

        resp = reduce_results(ctx, results)
        for extra in server_stats:
            resp.stats.merge(extra)
        resp.exceptions = exceptions
        resp.num_servers_queried = len(attempted)
        resp.num_servers_responded = responded
        resp.time_used_ms = (time.time() - start) * 1000.0
        if cache_key is not None:
            # put() itself refuses partial/errored responses
            self.result_cache.put(*cache_key, resp)
        return resp


def _mse_tables(parsed) -> set:
    """All physical table names an MSE query tree reads (from items,
    joins, subqueries, set operands) — the quota surface."""
    out: set = set()

    def walk(q):
        if q is None:
            return
        for attr in ("left", "right"):  # MseSetQuery operands
            walk(getattr(q, attr, None))
        fi = getattr(q, "from_item", None)
        if fi is not None:
            if getattr(fi, "table", None):
                out.add(fi.table)
            walk(getattr(fi, "subquery", None))
        for j in getattr(q, "joins", []) or []:
            item = getattr(j, "item", None) or getattr(j, "from_item", None)
            if item is not None:
                if getattr(item, "table", None):
                    out.add(item.table)
                walk(getattr(item, "subquery", None))

    walk(parsed)
    return out


def _error_response(code: int, message: str, start: float) -> BrokerResponse:
    resp = BrokerResponse()
    resp.exceptions = [{"errorCode": code, "message": message}]
    resp.time_used_ms = (time.time() - start) * 1000.0
    return resp


class StreamingMixin:
    """Per-block streaming consumption for selection queries (ref
    transport/grpc streaming + core/query/reduce/StreamingReduceService):
    server frames deserialize incrementally and row collection stops at
    OFFSET+LIMIT (remaining frames drain undecoded to keep the channel
    clean). Aggregations/group-bys fall back to the buffered path — their
    reduce needs all partials anyway."""

    def handle_streaming(self, sql: str) -> BrokerResponse:
        start = time.time()
        try:
            ctx = QueryContext.from_sql(sql)
        except (SqlParseError, ValueError):
            # joins/subqueries: same MSE delegation as the buffered path
            return self.handle(sql)
        if ctx.aggregations or ctx.group_by or ctx.distinct \
                or ctx.order_by \
                or ctx.options.get("useMultistageEngine",
                                   "").lower() == "true":
            return self.handle(sql)
        if not self._check_quota(ctx.table):
            return _error_response(
                429, f"QuotaExceededError: table {ctx.table} is over its "
                     f"QPS quota", start)
        route = self.routing.get_route(ctx.table)
        if route is None:
            return _error_response(
                190, f"TableDoesNotExistError: {ctx.table}", start)
        plan = route.route(ctx, unhealthy=self.failure_detector
                           .unhealthy_servers())
        request_id = self._next_id()
        needed = ctx.offset + ctx.limit
        results, exceptions, extra_stats = [], [], []
        rows_seen = 0
        blocks = 0
        for server, physical_table, names, extra in plan:
            conn = self.connections.get(server)
            if conn is None:
                exceptions.append({"errorCode": 427,
                                   "message": f"ServerNotConnected: {server}"})
                continue
            if self._selector is not None:
                self._selector.record_start(server)
            t0 = time.time()
            try:
                for frame in conn.request_streaming(
                        physical_table, sql, names, request_id, extra):
                    blocks += 1
                    if rows_seen >= needed:
                        continue  # drain to EOS, skip decoding
                    server_results, server_exc, stats = \
                        datatable.deserialize_results(frame)
                    exceptions.extend(server_exc)
                    if stats is not None:
                        extra_stats.append(stats)
                    for r in server_results:
                        results.append(r)
                        rows_seen += len(getattr(r, "rows", []))
                self.failure_detector.mark_success(server)
            except Exception as e:  # noqa: BLE001
                self.failure_detector.mark_failure(server)
                exceptions.append({"errorCode": 427,
                                   "message": f"ServerError: {e}"})
            finally:
                if self._selector is not None:
                    self._selector.record_end(server, time.time() - t0)
        resp = reduce_results(ctx, results)
        for s in extra_stats:
            resp.stats.merge(s)
        resp.exceptions = exceptions
        resp.num_servers_queried = len(plan)
        resp.num_servers_responded = len(plan) - sum(
            1 for e in exceptions if "ServerError" in e.get("message", ""))
        resp.time_used_ms = (time.time() - start) * 1000.0
        resp.num_streamed_blocks = blocks
        return resp


class StreamingBrokerRequestHandler(StreamingMixin, BrokerRequestHandler):
    """BrokerRequestHandler + the streaming response plane."""
