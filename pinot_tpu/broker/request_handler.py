"""Broker request handling: parse -> route -> scatter -> gather -> reduce.

Reference parity: pinot-broker requesthandler/
BaseSingleStageBrokerRequestHandler.java:280 (compile, authorize, route,
submit) + core/transport/QueryRouter.java:90 (scatter) +
core/query/reduce/BrokerReduceService.java:61 (gather/merge).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Function
from pinot_tpu.query.parser import SqlParseError, parse_sql
from pinot_tpu.query.reduce import BrokerResponse, reduce_results
from pinot_tpu.server import datatable
from pinot_tpu.server.query_server import ServerConnection
from pinot_tpu.broker.routing import BrokerRoutingManager


class BrokerRequestHandler:
    def __init__(self, routing: BrokerRoutingManager,
                 connections: Dict[str, ServerConnection],
                 max_fanout_threads: int = 16,
                 mse_dispatcher=None):
        self.routing = routing
        self.connections = connections
        #: multi-stage dispatcher (mse/dispatcher.py); when set, queries the
        #: single-stage grammar rejects (joins, subqueries) — or that opt in
        #: via useMultistageEngine — go through it (ref
        #: BrokerRequestHandlerDelegate engine selection)
        self.mse_dispatcher = mse_dispatcher
        self._pool = ThreadPoolExecutor(max_workers=max_fanout_threads)
        self._request_id = 0
        self._lock = threading.Lock()

    def _next_id(self) -> int:
        with self._lock:
            self._request_id += 1
            return self._request_id

    def handle(self, sql: str) -> BrokerResponse:
        start = time.time()
        try:
            query = parse_sql(sql)
            ctx = QueryContext.from_query(query)
        except (SqlParseError, ValueError) as e:
            if self.mse_dispatcher is not None:
                # delegate only if the multi-stage grammar accepts the query
                # (joins/subqueries); a genuine syntax error stays a 150
                try:
                    from pinot_tpu.mse.sql import parse_mse_sql
                    parsed = parse_mse_sql(sql)
                except (SqlParseError, ValueError):
                    return _error_response(
                        150, f"SQLParsingError: {e}", start)
                return self.mse_dispatcher.submit(sql, parsed)
            return _error_response(150, f"SQLParsingError: {e}", start)
        if self.mse_dispatcher is not None and \
                query.options.get("useMultistageEngine", "").lower() == "true":
            return self.mse_dispatcher.submit(sql)
        route = self.routing.get_route(ctx.table)
        if route is None:
            return _error_response(
                190, f"TableDoesNotExistError: {ctx.table}", start)

        plan = route.route(ctx)
        request_id = self._next_id()
        futures = []
        missing_servers = []
        for server, physical_table, segment_names, extra_filter in plan:
            conn = self.connections.get(server)
            if conn is None:
                # a silently skipped server would return a clean-looking
                # partial aggregate; surface it as a server error instead
                missing_servers.append(server)
                continue
            # the time-boundary predicate travels as a separate field and is
            # ANDed into the filter TREE server-side — splicing SQL text is
            # unsound (keywords inside identifiers/literals)
            futures.append(self._pool.submit(
                conn.request, physical_table, sql, segment_names,
                request_id, extra_filter))

        results, exceptions, server_stats = [], [], []
        for server in missing_servers:
            exceptions.append({"errorCode": 427,
                               "message": f"ServerNotConnected: {server}"})
        responded = 0
        for fut in futures:
            try:
                payload = fut.result(timeout=60)
                server_results, server_exc, extra = \
                    datatable.deserialize_results(payload)
                results.extend(server_results)
                exceptions.extend(server_exc)
                if extra is not None:
                    server_stats.append(extra)
                responded += 1
            except Exception as e:  # noqa: BLE001 — partial results semantics
                exceptions.append(
                    {"errorCode": 427, "message": f"ServerError: {e}"})

        resp = reduce_results(ctx, results)
        for extra in server_stats:
            resp.stats.merge(extra)
        resp.exceptions = exceptions
        resp.num_servers_queried = len(futures) + len(missing_servers)
        resp.num_servers_responded = responded
        resp.time_used_ms = (time.time() - start) * 1000.0
        return resp


def _error_response(code: int, message: str, start: float) -> BrokerResponse:
    resp = BrokerResponse()
    resp.exceptions = [{"errorCode": code, "message": message}]
    resp.time_used_ms = (time.time() - start) * 1000.0
    return resp
