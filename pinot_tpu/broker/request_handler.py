"""Broker request handling: parse -> route -> scatter -> gather -> reduce.

Reference parity: pinot-broker requesthandler/
BaseSingleStageBrokerRequestHandler.java:280 (compile, authorize, route,
submit) + core/transport/QueryRouter.java:90 (scatter) +
core/query/reduce/BrokerReduceService.java:61 (gather/merge).
"""
from __future__ import annotations

import threading
import time
import uuid
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as _fut_wait
from typing import Dict, List, Optional, Tuple

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Function
from pinot_tpu.query.parser import SqlParseError, parse_sql
from pinot_tpu.query.reduce import BrokerResponse, reduce_results
from pinot_tpu.server import datatable
from pinot_tpu.server.query_server import ServerConnection
from pinot_tpu.broker.routing import BrokerRoutingManager
from pinot_tpu.utils import errorcodes, tracing, trace_store
from pinot_tpu.utils.accounting import BrokerTimeoutError
from pinot_tpu.utils.failpoints import FailpointError, fire


def _overload_entry(server_exc) -> Optional[dict]:
    """The typed 211 admission rejection, when that is ALL the server
    said (a payload carrying real results or other errors is handled by
    the normal merge/fallback machinery, not the overload path)."""
    if not server_exc:
        return None
    entries = [e for e in server_exc if isinstance(e, dict)
               and e.get("errorCode") == errorcodes.SERVER_OVERLOADED]
    if len(entries) == len(server_exc):
        return entries[0]
    return None


def _retry_after_s(entry: dict) -> Optional[float]:
    """The in-band retryAfterMs hint from a 211 message, in seconds
    (format/parse single-sourced in utils/errorcodes.py)."""
    ms = errorcodes.parse_retry_after(entry.get("message", ""))
    return ms / 1000.0 if ms is not None else None


class _ScatterUnit:
    """One plan entry's lifecycle through scatter/gather: a primary
    attempt, at most one hedge — whole-set on a single replica when one
    holds everything, else SPLIT into per-replica child units covering
    disjoint segment subsets (partially-replicated layouts) — and, on
    hard failure, a one-shot retry that spawns fresh units covering only
    the still-unanswered segments. Dedup is per SEGMENT: a response
    merges iff none of its segments has already been answered by a clean
    twin (`answered` tracks the names), so overlapping partials can
    never double-count; `done` flips exactly once, when the whole set is
    answered or abandoned."""

    __slots__ = ("server", "table", "names", "extra", "retried",
                 "done", "hedge_tried", "hedged", "live", "fallback",
                 "answered", "parent", "children")

    def __init__(self, server: str, table: str, names: List[str],
                 extra: Optional[str], retried: bool = False,
                 parent: Optional["_ScatterUnit"] = None):
        self.server = server          # primary replica (hedges exclude it)
        self.table = table
        self.names = names
        self.extra = extra
        self.retried = retried        # retry units never hedge or re-retry
        self.done = False
        self.hedge_tried = False      # placement attempted (once only)
        self.hedged = False           # a hedge request is actually in flight
        self.live = 0                 # in-flight attempts
        #: an ERRORED payload received while a twin was still racing —
        #: held back so a clean twin can win, merged only if none does
        self.fallback = None
        #: segment names a clean response already covered (split hedges:
        #: first clean answer per segment wins, overlap discards)
        self.answered: set = set()
        #: set on split-hedge children; dedup/retry run on the parent
        self.parent = parent
        self.children: List["_ScatterUnit"] = []

    @property
    def logical(self) -> "_ScatterUnit":
        """The unit dedup/retry accounting lives on (self, or the parent
        for split-hedge children)."""
        return self.parent if self.parent is not None else self

    def pending_names(self) -> List[str]:
        return [n for n in self.names if n not in self.answered]

    def family_live(self) -> int:
        """In-flight attempts across the primary and every child."""
        return self.live + sum(c.live for c in self.children)


class BrokerRequestHandler:
    def __init__(self, routing: BrokerRoutingManager,
                 connections: Dict[str, ServerConnection],
                 max_fanout_threads: int = 16,
                 mse_dispatcher=None, failure_detector=None,
                 quota_manager=None, config=None, result_cache=None):
        self.routing = routing
        self.connections = connections
        self.config = config
        #: tier-1 whole-result cache (cache/broker_cache.py). Off unless a
        #: config enables pinot.broker.result.cache.enabled or a built
        #: cache is injected — failover semantics (a repeated query must
        #: re-exercise dead servers) are opt-out, not silently cached away.
        if result_cache is None and config is not None:
            from pinot_tpu.cache.broker_cache import BrokerResultCache
            from pinot_tpu.utils.metrics import get_registry
            result_cache = BrokerResultCache.from_config(
                config, metrics=get_registry("broker"))
        self.result_cache = result_cache
        from pinot_tpu.utils.metrics import get_registry
        self._metrics = get_registry("broker")
        #: pruned-to-zero memo (cache/broker_cache.py NegativeResultCache)
        #: — independent of the whole-result cache and on by default
        from pinot_tpu.cache.broker_cache import NegativeResultCache
        # share THIS broker's metric label with the result cache so the
        # two caches' series correlate; fall back to a fresh label when
        # no result cache exists to borrow from
        from pinot_tpu.cache.broker_cache import _broker_ids
        neg_labels = getattr(self.result_cache, "labels", None) or \
            {"broker": f"b{next(_broker_ids)}"}
        if config is not None:
            self._negative_cache = NegativeResultCache.from_config(
                config, metrics=self._metrics, labels=neg_labels)
            self._hedge_enabled = config.get_bool(
                "pinot.broker.hedge.enabled")
            self._hedge_min_s = config.get_int(
                "pinot.broker.hedge.delay.min.ms") / 1000.0
            self._hedge_max_s = config.get_int(
                "pinot.broker.hedge.delay.max.ms") / 1000.0
            self._default_timeout_ms = float(
                config.get_int("pinot.broker.timeout.ms"))
            self._trace_enabled = config.get_bool(
                "pinot.trace.enabled", True)
            self._slow_threshold_ms = config.get_float(
                "pinot.broker.slow.query.threshold.ms")
            self._trace_capacity = config.get_int(
                "pinot.trace.store.capacity")
            self._slo_p99_ms = config.get_float("pinot.slo.query.p99.ms")
        else:
            self._negative_cache = NegativeResultCache(
                metrics=self._metrics, labels=neg_labels)
            self._hedge_enabled = False
            self._hedge_min_s, self._hedge_max_s = 0.025, 1.0
            self._default_timeout_ms = 60000.0
            self._trace_enabled = True
            self._slow_threshold_ms = 10000.0
            self._trace_capacity = None
            self._slo_p99_ms = 0.0
        #: query ids must be unique ACROSS brokers — two brokers' counters
        #: both start at 1, and the server's accountant keys cancels by id
        self._broker_nonce = uuid.uuid4().hex[:6]
        #: per-table QPS limits (ref queryquota/; None = no quotas)
        self.quota_manager = quota_manager
        #: logical table -> tenant tag (TableConfig tenants.server):
        #: shipped with every server request so the scheduler charges
        #: the right weighted-fair group (cluster wiring populates it)
        self.tenants: Dict[str, str] = {}
        #: adaptive selector stats feed (routing.selector, may be None)
        self._selector = getattr(routing, "selector", None)
        #: per-table retry/hedge budget (broker/adaptive.py RetryBudget):
        #: clean primary responses refill it, every retry/hedge spends
        #: from it — failures cannot amplify into retry storms
        from pinot_tpu.broker.adaptive import RetryBudget
        self._retry_budget = RetryBudget.from_config(
            config, metrics=self._metrics)
        #: multi-stage dispatcher (mse/dispatcher.py); when set, queries the
        #: single-stage grammar rejects (joins, subqueries) — or that opt in
        #: via useMultistageEngine — go through it (ref
        #: BrokerRequestHandlerDelegate engine selection)
        self.mse_dispatcher = mse_dispatcher
        if failure_detector is None:
            from pinot_tpu.broker.failure_detector import \
                ConnectionFailureDetector
            failure_detector = ConnectionFailureDetector()
        self.failure_detector = failure_detector
        self._pool = ThreadPoolExecutor(max_workers=max_fanout_threads)
        #: cancels get their OWN tiny pool: at deadline expiry the
        #: fan-out pool's threads are blocked on the very reads being
        #: cancelled, so a cancel queued there would fire only after the
        #: abandoned read drained — defeating its purpose
        self._cancel_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="broker-cancel")
        self._request_id = 0
        self._lock = threading.Lock()

    def _next_id(self) -> int:
        with self._lock:
            self._request_id += 1
            return self._request_id

    def on_segments_replaced(self, table: str) -> None:
        """Cache-coherence hook for a segment swap (minion merge-rollup /
        purge commit): the routing epoch already moved, making result-
        cache entries unaddressable; negative entries for the table are
        additionally DROPPED — a "prunes to zero" memo recorded against
        the old segment set must not linger in budget either."""
        self._negative_cache.drop_table(table)

    def _hybrid_offline_enabled(self) -> bool:
        """Hybrid offline-partial caching rides the result cache; the
        knob exists to switch the behavior off independently."""
        if self.config is not None:
            return self.config.get_bool(
                "pinot.broker.result.cache.hybrid.offline", True)
        return True

    def _check_quota(self, table: str) -> Optional[str]:
        """QPS quota on the LOGICAL name — quotas register unsuffixed, so
        a _OFFLINE/_REALTIME-suffixed query must hit the same bucket
        (ref HelixExternalViewBasedQueryQuotaManager: over-quota queries
        are rejected, not queued). Returns the rejection reason (naming
        the over-budget scope — table or tenant) or None when admitted."""
        if self.quota_manager is None:
            return None
        from pinot_tpu.models import base_table_name
        return self.quota_manager.check(base_table_name(table))

    def _tenant_of(self, table: str) -> Optional[str]:
        """The tenant tag shipped with every server request (weighted-
        fair scheduling group server-side); from the handler's own map
        first, the quota manager's table->tenant map as fallback."""
        from pinot_tpu.models import base_table_name
        base = base_table_name(table)
        tenant = self.tenants.get(base)
        if tenant is None and self.quota_manager is not None:
            tenant = self.quota_manager.tenant_of(base)
        return tenant

    def _timeout_ms(self, ctx: QueryContext) -> float:
        """End-to-end budget for one query, highest precedence first:
        OPTION(timeoutMs=...) / SET timeoutMs, a per-table config
        override (`pinot.broker.timeout.ms.<logicalTable>`), then
        `pinot.broker.timeout.ms`."""
        opt = ctx.options.get("timeoutMs")
        if opt:
            try:
                return max(1.0, float(opt))
            except ValueError:
                pass
        if self.config is not None:
            per_table = self.config.get(
                f"pinot.broker.timeout.ms.{ctx.table}")
            if per_table is not None:
                return max(1.0, float(per_table))
        return self._default_timeout_ms

    def _hedge_delay_s(self) -> Optional[float]:
        """Adaptive hedge trigger: p95 over the selector's pooled
        per-server latency reservoirs (true per-request tails, not
        smoothed means), clamped to the configured floor/ceiling. None
        when hedging is off — including AUTO-disabled: under brownout
        (rung 1) or while any server's overload horizon is open,
        speculative duplicate load is exactly the wrong medicine for a
        fleet already shedding (maybe_hedge re-checks per tick, so the
        gate is live mid-gather too)."""
        if not self._hedge_enabled:
            return None
        from pinot_tpu.health.brownout import engaged
        if engaged("broker", "hedge_off") \
                or self.failure_detector.any_overloaded():
            return None
        base = (self._selector.latency_quantile(0.95)
                if self._selector is not None else 0.0)
        return min(max(base, self._hedge_min_s), self._hedge_max_s)

    def _spend_retry(self, table: str) -> bool:
        """One retry/hedge attempt's budget withdrawal. The
        `broker.retry.budget` failpoint fires on every withdrawal —
        seeded chaos forces exhaustion deterministically (armed with
        error=FailpointError), and its decision journal replays
        byte-identical."""
        try:
            fire("broker.retry.budget", table=table)
        except FailpointError:
            self._metrics.add_meter("broker_retry_budget_exhausted")
            return False
        return self._retry_budget.try_withdraw(table)

    @staticmethod
    def _phase(phase: str, detail: str = "") -> None:
        """Update the in-flight registry for the CURRENT query's trace
        (no-op when tracing is off) — /debug/queries reads it."""
        req = tracing.current_request()
        if req is not None:
            trace_store.get_inflight("broker").phase(
                req.trace_id, phase, detail)

    def handle(self, sql: str) -> BrokerResponse:
        """Traced entry point: every query runs under a shadow span tree
        (tracing.RequestTrace). trace=true queries return the stitched
        cross-process tree as traceInfo; queries at/over
        pinot.broker.slow.query.threshold.ms retain their tree in the
        broker trace store (tail-based capture) and emit a structured
        slow-query log line even with trace=false. With
        pinot.trace.enabled=false none of this machinery exists."""
        if not self._trace_enabled:
            resp = self._handle_inner(sql)
            self._meter_response(resp)
            return resp
        rt = tracing.RequestTrace(sampled=False)
        inflight = trace_store.get_inflight("broker")
        inflight.begin(rt.trace_id, sql=sql, trace_id=rt.trace_id)
        try:
            with rt:
                resp = self._handle_inner(sql)
        finally:
            inflight.end(rt.trace_id)
        self._meter_response(resp)
        dur = rt.root.duration_ms
        self._metrics.add_timing("broker_query_ms", dur,
                                 exemplar=rt.trace_id)
        if self._slo_p99_ms and dur > self._slo_p99_ms:
            # the latency-SLO burn numerator (health/slo.py): a
            # windowed bad-queries counter, counted where the latency
            # is measured
            self._metrics.add_meter("slo_latency_bad")
        slow = (self._slow_threshold_ms > 0
                and dur >= self._slow_threshold_ms)
        if rt.sampled:
            resp.trace = rt.to_dict()
        if rt.sampled or slow:
            trace_store.get_store("broker", self._trace_capacity).record(
                rt.trace_id, rt.to_dict(), sql=sql, duration_ms=dur,
                slow=slow,
                extra={"partialResult": bool(resp.partial_result)})
            if slow:
                trace_store.log_slow_query(
                    "broker", rt.trace_id, sql, dur,
                    self._slow_threshold_ms,
                    partialResult=bool(resp.partial_result),
                    exceptions=len(resp.exceptions or []))
                self._metrics.add_meter("slow_queries")
        return resp

    def _meter_response(self, resp) -> None:
        """Per-response counters the SLO error-rate burn reads
        (health/slo.py _ERROR_FAMILIES / _QUERY_FAMILIES): total
        queries, responses carrying any exception, and responses
        carrying an errorCode-250 (deadline) entry specifically."""
        self._metrics.add_meter("broker_queries")
        excs = [e for e in (resp.exceptions or []) if isinstance(e, dict)]
        if excs:
            self._metrics.add_meter("broker_query_errors")
        if any(e.get("errorCode") == errorcodes.EXECUTION_TIMEOUT
               for e in excs):
            self._metrics.add_meter("broker_error_code_250")
        if any(e.get("errorCode") == errorcodes.SERVER_OVERLOADED
               for e in excs):
            # the brownout shed-rate numerator: overload rejections that
            # no replica absorbed and surfaced to the client as partials
            self._metrics.add_meter("broker_overload_partials")

    def _timed_request(self, conn, server, physical_table, sql,
                       segment_names, request_id, extra_filter,
                       deadline=None, query_id=None, tenant=None,
                       group=None, trace_wire=None):
        """conn.request wrapped with adaptive-selector stats (latency +
        in-flight, ref adaptiveserverselector's ServerRoutingStats).
        The remaining budget is computed HERE, on the pool thread at
        send time — computing it at submit time would inflate the
        shipped budget by however long the task sat in the fan-out
        queue. group: the replica-group index this scatter targets —
        the `broker.group.scatter` chaos site fires with it, so a
        schedule can kill exactly one fault domain (`where={"group": 0}`)
        and the failure rides the normal connection-error path."""
        fire("broker.scatter.before", server=server, table=physical_table)
        if group is not None:
            fire("broker.group.scatter", server=server,
                 table=physical_table, group=group)
        timeout_ms = (max(1.0, (deadline - time.time()) * 1000.0)
                      if deadline is not None else None)
        sel = self._selector
        if sel is None:
            return conn.request(physical_table, sql, segment_names,
                                request_id, extra_filter,
                                timeout_ms=timeout_ms, query_id=query_id,
                                tenant=tenant, trace_ctx=trace_wire)
        sel.record_start(server)
        t0 = time.time()
        try:
            return conn.request(physical_table, sql, segment_names,
                                request_id, extra_filter,
                                timeout_ms=timeout_ms, query_id=query_id,
                                tenant=tenant, trace_ctx=trace_wire)
        finally:
            sel.record_end(server, time.time() - t0)

    def _handle_inner(self, sql: str) -> BrokerResponse:
        start = time.time()
        req_trace = tracing.current_request()
        root_h = tracing.capture()
        self._phase("parse")
        try:
            query = parse_sql(sql)
            ctx = QueryContext.from_query(query)
        except (SqlParseError, ValueError) as e:
            if self.mse_dispatcher is not None:
                # delegate only if the multi-stage grammar accepts the query
                # (joins/subqueries); a genuine syntax error stays a 150
                try:
                    from pinot_tpu.mse.sql import parse_mse_sql
                    parsed = parse_mse_sql(sql)
                except (SqlParseError, ValueError):
                    return _error_response(
                        errorcodes.SQL_PARSING,
                        f"SQLParsingError: {e}", start)
                # MSE queries are NOT a quota bypass: meter EVERY table
                # the tree reads (set operands + subquery roots included)
                # in ONE all-or-nothing acquisition — a rejection must
                # not drain any table's (or the shared tenant's) budget,
                # and one N-table query is one query per tenant ceiling
                if self.quota_manager is not None:
                    from pinot_tpu.models import base_table_name
                    reason = self.quota_manager.check_many(
                        [base_table_name(t) for t in _mse_tables(parsed)])
                    if reason:
                        return _error_response(
                            errorcodes.QUOTA_EXCEEDED,
                            f"QuotaExceededError: {reason}", start)
                # the MSE query enters with the same end-to-end budget
                # resolution as the single-stage path: OPTION(timeoutMs)
                # wins inside the dispatcher, this broker's configured
                # default is the fallback
                return self.mse_dispatcher.submit(
                    sql, parsed, default_timeout_ms=self._default_timeout_ms)
            return _error_response(errorcodes.SQL_PARSING,
                                   f"SQLParsingError: {e}", start)
        if req_trace is not None:
            # the client's trace=true upgrades the shadow trace to a
            # sampled one: the stitched tree returns as traceInfo
            if ctx.options.get("trace", "").lower() == "true":
                req_trace.sampled = True
            root_h.set(table=ctx.table)
        quota_reason = self._check_quota(ctx.table)
        if quota_reason:
            return _error_response(
                errorcodes.QUOTA_EXCEEDED,
                f"QuotaExceededError: {quota_reason}", start)
        if self.mse_dispatcher is not None and \
                query.options.get("useMultistageEngine", "").lower() == "true":
            return self.mse_dispatcher.submit(
                sql, default_timeout_ms=self._default_timeout_ms)
        self._phase("route", ctx.table)
        route = self.routing.get_route(ctx.table)
        if route is None:
            return _error_response(
                errorcodes.TABLE_DOES_NOT_EXIST,
                f"TableDoesNotExistError: {ctx.table}", start)

        # -- tier-1 whole-result cache ---------------------------------
        # keyed by (query fingerprint, table, routing epoch): the epoch
        # hashes the segment set + versions, so segment add/replace/remove
        # invalidates by construction. Tables with consuming segments are
        # skipped unless cache_realtime — appends don't move the epoch.
        cache_key = None
        offline_key = None  # hybrid offline-partial cache key
        cacheable = False
        if self.result_cache is not None and self.result_cache.enabled \
                and not ctx.explain \
                and ctx.options.get("trace", "").lower() != "true":
            from pinot_tpu.cache.broker_cache import cache_bypassed
            cacheable = not cache_bypassed(ctx.options)
            if cacheable and (self.result_cache.cache_realtime
                              or not route.has_realtime):
                epoch = route.epoch()
                if not epoch.startswith("<torn:"):
                    # a torn epoch never repeats: a get can't hit and a
                    # put would leak an unaddressable entry — skip both.
                    # Under brownout rung 2 an expired-but-retained
                    # entry may serve, flagged staleResult=true: a
                    # correct-but-old dashboard beats a shed query.
                    from pinot_tpu.health.brownout import engaged
                    cache_key = (ctx.fingerprint(), ctx.table, epoch)
                    hit = self.result_cache.get(
                        *cache_key,
                        allow_stale=engaged("broker", "stale_cache"))
                    if hit is not None:
                        hit.cache_hit = True
                        if hit.stale_result:
                            self._metrics.add_meter("stale_results_served")
                        hit.time_used_ms = (time.time() - start) * 1000.0
                        return hit

        # -- negative cache: pruned-to-zero plans ----------------------
        # independent of (and cheaper than) the whole-result cache: a
        # dashboard misfire whose pruning selects NO segment has an empty
        # answer by construction — memoize the emptiness, epoch-keyed,
        # and skip routing + scatter + reduce on repeats
        neg_key = None
        if self._negative_cache.enabled and not ctx.explain \
                and ctx.options.get("trace", "").lower() != "true":
            from pinot_tpu.cache.broker_cache import cache_bypassed
            if not cache_bypassed(ctx.options):
                neg_epoch = route.epoch()
                if not neg_epoch.startswith("<torn:"):
                    neg_key = (ctx.fingerprint(), ctx.table, neg_epoch)
                    if self._negative_cache.hit(*neg_key):
                        resp = reduce_results(ctx, [])
                        resp.cache_hit = True
                        resp.time_used_ms = (time.time() - start) * 1000.0
                        return resp

        plan = route.route(ctx, unhealthy=self.failure_detector
                           .unhealthy_servers())
        if neg_key is not None and not plan and route.prunes_to_zero(ctx):
            self._negative_cache.put(*neg_key)
        request_id = self._next_id()
        #: unique across brokers — the server accountant keys cancels on it
        query_id = f"{self._broker_nonce}-{request_id}"
        #: end-to-end budget: servers get the REMAINING slice at send
        #: time, waits below derive from it, and expiry cancels leftovers
        timeout_ms = self._timeout_ms(ctx)
        deadline = start + timeout_ms / 1000.0
        hedge_delay_s = self._hedge_delay_s()
        hedge_at = None if hedge_delay_s is None else start + hedge_delay_s
        results, exceptions, server_stats = [], [], []
        responded = 0
        attempted: set = set()
        failed_servers: set = set()

        # -- hybrid-table offline-partial cache ------------------------
        # when the whole result is uncacheable because of a consuming
        # side, the OFFLINE side's merged partial still is: keyed by the
        # offline epoch, so only the realtime entries re-scatter. The
        # partial is the raw per-server result list — reduce merges it
        # with the realtime side's fresh results exactly as if the
        # offline servers had answered.
        offline_results: list = []
        offline_stats: list = []
        offline_failed = [False]
        if cacheable and cache_key is None \
                and route.offline is not None and route.has_realtime \
                and self._hybrid_offline_enabled():
            off_epoch = route.offline_epoch()
            if not off_epoch.startswith("<torn:"):
                key = (ctx.fingerprint(), ctx.table, off_epoch)
                # READ whenever the epoch is clean: stored partials are
                # complete by construction (see the PUT gate), so during
                # an offline-server outage the cache is strictly better
                # than the degraded scatter routing would attempt
                cached = self.result_cache.get_offline_partial(*key)
                if cached is not None:
                    cached_results, cached_stats = cached
                    results.extend(cached_results)
                    if cached_stats is not None:
                        server_stats.append(cached_stats)
                    plan = [e for e in plan
                            if not e[1].endswith("_OFFLINE")]
                else:
                    # PUT only when the plan covers every unpruned
                    # offline segment: a segment with no placeable
                    # replica is silently dropped from the plan (routing
                    # tolerates it; the query degrades), but the epoch
                    # hashes the segment SET, not placement — a partial
                    # missing those rows would be served as complete
                    # until TTL
                    planned_off = {n for _srv, tbl, names, _ef in plan
                                   if tbl.endswith("_OFFLINE")
                                   for n in names}
                    if planned_off == route.offline_segments_for(ctx):
                        offline_key = key

        units: List[_ScatterUnit] = []
        #: live future -> (unit, server, is_hedge, attempt id, span)
        fut_map: Dict = {}
        attempt_seq = [0]
        tenant = self._tenant_of(ctx.table)
        if req_trace is not None:
            # /debug/queries actionability: the in-flight entry carries
            # WHOSE query this is and how much budget remains
            trace_store.get_inflight("broker").annotate(
                req_trace.trace_id, tenant=tenant, deadline=deadline)

        #: per-query memo for (table, server) -> group index: the
        #: derivation scans every segment's replica list, which is too
        #: expensive to repeat per scatter ATTEMPT on large tables
        #: (non-grouped tables short-circuit to None without scanning)
        group_idx_memo: Dict[tuple, Optional[int]] = {}

        def group_of(table: str, server: str) -> Optional[int]:
            key = (table, server)
            if key not in group_idx_memo:
                group_idx_memo[key] = route.group_index_of(table, server)
            return group_idx_memo[key]

        def group_exclude(table: str, servers) -> set:
            """Whole-group demotion: for replica-group tables the fault
            domain of every failed server is excluded, so a retry/hedge
            re-scatters onto a SURVIVING group instead of splitting the
            query across a half-dead one."""
            out: set = set()
            for s in servers:
                out |= route.group_peers(table, s)
            return out

        def launch(unit: _ScatterUnit, server: str,
                   is_hedge: bool = False) -> bool:
            conn = self.connections.get(server)
            if conn is None:
                if is_hedge:
                    # a hedge that can't launch is simply no hedge — the
                    # primary is still racing and may return the whole
                    # answer; an exception here would poison it
                    return False
                attempted.add(server)
                # a silently skipped server would return a clean-looking
                # partial aggregate; surface it as a server error
                exceptions.append(
                    {"errorCode": errorcodes.SERVER_ERROR,
                     "message": f"ServerNotConnected: {server}"})
                if unit.table.endswith("_OFFLINE"):
                    offline_failed[0] = True
                return False
            attempted.add(server)
            # per-ATTEMPT id: server-side registration and cancels key on
            # it, so cancelling a hedge loser can never tombstone a later
            # retry of this query that lands on the same server
            attempt_seq[0] += 1
            aid = f"{query_id}.{attempt_seq[0]}"
            # one span per scatter ATTEMPT: hedge/retry attempts appear
            # as siblings; the server's own tree grafts under it when
            # the response lands (process). The wire context carries a
            # fresh parent span id per attempt.
            sp = trace_wire = None
            if root_h is not None:
                sp = root_h.child(
                    "ServerScatter", server=server, table=unit.table,
                    segments=len(unit.names or ()), attempt=aid,
                    **({"hedge": True} if is_hedge else {}),
                    **({"retry": True} if unit.retried else {}))
                trace_wire = req_trace.wire_context()
            # the time-boundary predicate travels as a separate field,
            # ANDed into the filter TREE server-side — splicing SQL
            # text is unsound (keywords inside identifiers/literals).
            # The server receives the REMAINING budget, not the original:
            # queue time and earlier rounds already spent part of it
            # (_timed_request derives it from the deadline at send time).
            fut = self._pool.submit(
                self._timed_request, conn, server, unit.table, sql,
                unit.names, request_id, unit.extra, deadline, aid,
                tenant, group_of(unit.table, server), trace_wire)
            fut_map[fut] = (unit, server, is_hedge, aid, sp)
            unit.live += 1
            return True

        def cancel_attempt(server: str, aid: str) -> None:
            conn = self.connections.get(server)
            if conn is not None:
                self._cancel_pool.submit(conn.cancel, aid)

        def cancel_family(unit: _ScatterUnit) -> None:
            """The race resolved: stop every losing attempt of this
            logical unit (primary, whole-set hedge, split-hedge children)
            server-side so abandoned work frees its scheduler thread.
            Attempt-scoped, so nothing else of this query is touched."""
            for _f, (u, server, _h, aid, _sp) in list(fut_map.items()):
                if u is unit or u.parent is unit:
                    cancel_attempt(server, aid)

        def merge(unit: _ScatterUnit, server_results, server_exc,
                  stats_extra) -> None:
            nonlocal responded
            results.extend(server_results)
            if unit.table.endswith("_OFFLINE"):
                if server_exc:
                    offline_failed[0] = True
                else:
                    offline_results.extend(server_results)
                    if stats_extra is not None:
                        offline_stats.append(stats_extra)
            exceptions.extend(server_exc)
            if stats_extra is not None:
                server_stats.append(stats_extra)
            responded += 1

        def typed_failure(error, overload: Optional[dict],
                          suffix: str = "") -> dict:
            """The exception entry a dead logical unit surfaces: an
            overload rejection stays a typed 211 (its retryAfterMs hint
            intact) — NEVER a raw 427, which would read as a dead
            server and double-penalize a merely saturated one."""
            if overload is not None:
                return {"errorCode": errorcodes.SERVER_OVERLOADED,
                        "message": str(overload.get("message", error))
                        + suffix}
            return {"errorCode": errorcodes.SERVER_ERROR,
                    "message": f"ServerError: {error}{suffix}"}

        def resolve_failed(L: _ScatterUnit, error,
                           overload: Optional[dict] = None) -> None:
            """Every attempt of logical unit L is dead: salvage held-back
            errored payloads for still-unanswered segment sets, then
            retry ONLY the unanswered remainder on surviving replicas —
            sharing, not resetting, the original deadline budget, and
            PAYING for the retry from the per-table budget (exhausted
            budget = typed partial, not re-offered load). For grouped
            tables the exclusion demotes each failed server's whole
            group, so the re-scatter lands on a surviving group.
            overload: the typed 211 entry when the unit died of
            admission rejection — retried on at most one other replica
            (retry units never re-retry) and surfaced typed."""
            L.done = True
            for c in L.children:
                c.done = True
            if L.table.endswith("_OFFLINE"):
                offline_failed[0] = True
            for cand in (L, *L.children):
                if cand.fallback is not None \
                        and not (set(cand.names) & L.answered):
                    # a server DID answer (with errors) and no clean twin
                    # covered these segments: better its partial than
                    # re-failing
                    merge(cand, *cand.fallback)
                    L.answered.update(cand.names)
            pending = L.pending_names()
            if not pending:
                return
            if L.retried:
                exceptions.append(typed_failure(error, overload))
                return
            if not self._spend_retry(L.table):
                # budget dry: surface typed instead of amplifying —
                # a fleet-wide failure under load must converge offered
                # load toward the organic rate, not multiply it
                exceptions.append(typed_failure(
                    error, overload, suffix=" (retry budget exhausted)"))
                return
            # exclude everything known-bad: this round's failures, the
            # detector's unhealthy set, AND every failed server's whole
            # replica group — or the single retry can land on another
            # dead server (or split across a half-dead fault domain)
            # while a healthy group exists
            exclude = failed_servers | \
                self.failure_detector.unhealthy_servers() | \
                group_exclude(L.table, failed_servers)
            rerouted, unplaced = route.reroute_segments(
                L.table, pending, exclude=exclude,
                extra_filter=L.extra)
            if unplaced:
                # segments with no surviving replica: surface the
                # loss instead of a clean-looking partial answer
                exceptions.append(typed_failure(
                    error, overload, suffix=f" (segments lost: {unplaced})"))
            for rserver, rtable, rnames, rextra in rerouted:
                child = _ScatterUnit(rserver, rtable, rnames, rextra,
                                     retried=True)
                units.append(child)
                if launch(child, rserver):
                    self._metrics.add_meter("broker_retries_issued")
                else:
                    child.done = True

        def process(fut) -> None:
            unit, server, is_hedge, _aid, sp = fut_map.pop(fut)
            unit.live -= 1
            L = unit.logical
            try:
                # process() only sees completed futures today (the
                # gather loop waits FIRST_COMPLETED), but the wait is
                # bounded by the query's remaining budget anyway so a
                # future that lies about being done can never park the
                # broker thread past the deadline
                payload = fut.result(
                    timeout=max(0.0, deadline - time.time()) + 1.0)
                server_results, server_exc, stats_extra, server_trace = \
                    datatable.deserialize_results_ex(payload)
            except Exception as e:  # noqa: BLE001 — partial results
                if sp is not None:
                    sp.end(error=f"{type(e).__name__}: {e}",
                           outcome="failed")
                # connection-level failure: mark unhealthy (routing skips
                # it until the backoff expires, ref
                # ConnectionFailureDetector — and for grouped tables the
                # selector stops picking the whole group next query)
                self.failure_detector.mark_failure(server)
                failed_servers.add(server)
                if unit.parent is not None:
                    unit.done = True
                if L.done or L.family_live() > 0:
                    # a twin already merged (or is still racing): this
                    # failure loses/defers — it must NOT poison the
                    # offline-partial cache, the data is (or may yet be)
                    # complete from the twin(s)
                    return
                resolve_failed(L, e)
                return
            overload = _overload_entry(server_exc)
            if overload is not None:
                # typed 211 admission rejection: the server is alive and
                # shedding — cool it lightly (NOT a failure mark), stop
                # hedging into the saturation, and retry the unit on at
                # most one other replica if the budget allows; otherwise
                # the rejection surfaces as a typed partial, never a 427
                self._metrics.add_meter("broker_overload_rejections")
                self.failure_detector.mark_overload(
                    server, retry_after_s=_retry_after_s(overload))
                if sp is not None:
                    sp.graft(server_trace)
                    sp.end(outcome="overloaded")
                if unit.parent is not None:
                    unit.done = True
                if L.done or L.family_live() > 0:
                    # a twin already merged (or is still racing): this
                    # rejection loses/defers
                    return
                resolve_failed(L, overload.get("message", "overloaded"),
                               overload=overload)
                return
            self.failure_detector.mark_success(server)
            if unit.parent is None and not unit.retried and not is_hedge:
                # a clean-channel primary response refills the table's
                # retry budget (errored payloads still count: the
                # SERVER answered — amplification risk is about load,
                # not correctness)
                self._retry_budget.deposit(unit.table)
            if sp is not None:
                # the server's own span tree stitches under this
                # attempt's scatter span — ONE cross-process tree
                sp.graft(server_trace)
                sp.end()
            if L.done:
                # hedge race loser — drop, never double-merge
                if sp is not None:
                    sp.set(outcome="loser")
                return
            if unit.parent is None:
                # primary / whole-set hedge attempt: covers ALL of L's
                # segments, so it can merge only while NO child answered
                # (a merged overlap would double-count those segments)
                if L.answered:
                    if L.family_live() == 0:
                        # children died after partially answering and
                        # this full payload can't be split: re-scatter
                        # the unanswered remainder
                        resolve_failed(L, "overlapping partial discarded")
                    return
                if server_exc and L.family_live() > 0:
                    # an ERRORED payload while a twin still races: hold
                    # it back — first CLEAN response wins; this merges
                    # only if no twin delivers a clean answer
                    unit.fallback = (server_results, server_exc,
                                     stats_extra)
                    return
                L.done = True
                for c in L.children:
                    c.done = True
                if L.hedged:
                    self._metrics.add_meter(
                        "hedge_won" if is_hedge else "hedge_wasted")
                    cancel_family(L)
                    if sp is not None:
                        sp.set(outcome="winner")
                merge(unit, server_results, server_exc, stats_extra)
                return
            # split-hedge child: per-segment dedup — merge iff none of
            # its (disjoint-by-construction) segments was answered yet
            if set(unit.names) & L.answered:
                if sp is not None:
                    sp.set(outcome="loser")
                return
            if server_exc and (unit.live > 0 or L.live > 0):
                unit.fallback = (server_results, server_exc, stats_extra)
                return
            unit.done = True
            if sp is not None and is_hedge:
                sp.set(outcome="winner")
            merge(unit, server_results, server_exc, stats_extra)
            L.answered.update(unit.names)
            if not L.pending_names():
                # the child set covered everything: the split hedge won
                L.done = True
                for c in L.children:
                    c.done = True
                self._metrics.add_meter("hedge_won")
                cancel_family(L)

        def maybe_hedge() -> None:
            """Past the adaptive delay, duplicate each still-pending
            primary onto different healthy replica(s) ("The Tail at
            Scale"): first clean response wins per segment, losers are
            cancelled. One hedge round per unit — whole-set on a single
            replica when one holds everything, else SPLIT into disjoint
            child units (partially-replicated layouts, where replica
            groups make partial overlap the norm)."""
            if hedge_at is None or time.time() < hedge_at:
                return
            if self._hedge_delay_s() is None:
                # live auto-disable: a server reported overload (or the
                # brownout ladder climbed) AFTER this query started —
                # speculative duplicate load must stop immediately, not
                # at the next query
                return
            for unit in list(units):
                if unit.done or unit.live == 0 or unit.hedge_tried \
                        or unit.retried or unit.parent is not None:
                    continue
                unit.hedge_tried = True
                exclude = ({unit.server} | failed_servers
                           | self.failure_detector.unhealthy_servers()
                           | group_exclude(unit.table, [unit.server]))
                entries, unplaced = route.reroute_segments(
                    unit.table, unit.names, exclude=exclude,
                    extra_filter=unit.extra)
                if unplaced or not entries:
                    continue  # some segment has no other healthy replica
                if (deadline - time.time()) * 1000.0 < 1.0:
                    continue  # no budget left to hedge into
                if not self._spend_retry(unit.table):
                    continue  # hedges are retries too: budget governs both
                if len(entries) == 1:
                    if launch(unit, entries[0][0], is_hedge=True):
                        unit.hedged = True
                        self._metrics.add_meter("hedge_issued")
                    continue
                # split hedge: one child per replica, disjoint segment
                # subsets that together cover the whole pending set
                launched = False
                for hserver, htable, hnames, hextra in entries:
                    child = _ScatterUnit(hserver, htable, hnames, hextra,
                                         parent=unit)
                    child.hedge_tried = True
                    if launch(child, hserver, is_hedge=True):
                        unit.children.append(child)
                        units.append(child)
                        launched = True
                    else:
                        child.done = True
                if launched:
                    unit.hedged = True
                    self._metrics.add_meter("hedge_issued")
                    self._metrics.add_meter("hedge_split")

        self._phase("scatter", ctx.table)
        for server, physical_table, segment_names, extra_filter in plan:
            unit = _ScatterUnit(server, physical_table, segment_names,
                                extra_filter)
            units.append(unit)
            if not launch(unit, server):
                unit.done = True

        self._phase("gather", ctx.table)
        # -- gather: deadline-derived waits, no per-future magic numbers.
        # Exit as soon as every UNIT resolved — a hedge race's losing
        # future may stay in flight long after its unit completed, and
        # waiting for it would forfeit the hedge's entire latency win.
        while fut_map and not all(u.done for u in units):
            now = time.time()
            if now >= deadline:
                break
            wait_until = deadline
            if hedge_at is not None and any(
                    not u.done and not u.hedge_tried and not u.retried
                    for u in units):
                wait_until = min(wait_until, hedge_at)
            done, _pending = _fut_wait(list(fut_map),
                                       timeout=max(0.0, wait_until - now),
                                       return_when=FIRST_COMPLETED)
            for fut in done:
                process(fut)
            maybe_hedge()

        abandoned: Dict[int, Tuple[_ScatterUnit, List[str]]] = {}
        for fut, (unit, server, _h, aid, sp) in fut_map.items():
            if not unit.done:
                abandoned.setdefault(id(unit), (unit, []))[1].append(server)
                cancel_attempt(server, aid)
                if sp is not None:
                    sp.end(outcome="abandoned")
            elif sp is not None:
                # hedge-race loser whose future is still in flight when
                # the gather exits (process() will never run for it):
                # close its span honestly — duration = time until the
                # race resolved against it, no server tree
                sp.end(outcome="loser")
        if abandoned:
            # deadline expired with work outstanding: surface a typed
            # 250 partial per abandoned unit, cancel the server-side
            # work (attempt-scoped), and cool the slow servers so the
            # next queries prefer other replicas
            for unit, servers in abandoned.values():
                unit.done = True
                if unit.fallback is not None \
                        and not (set(unit.names) & unit.logical.answered):
                    # better an errored answer a server actually gave
                    # than nothing (overlap-guarded: segments a clean
                    # split-hedge twin already answered must not merge
                    # twice) — the 250 below still records that the
                    # clean twin never arrived
                    merge(unit, *unit.fallback)
                    unit.logical.answered.update(unit.names)
                if unit.table.endswith("_OFFLINE"):
                    offline_failed[0] = True
                for server in servers:
                    self.failure_detector.mark_timeout(server)
                exceptions.append({
                    "errorCode": BrokerTimeoutError.ERROR_CODE,
                    "message": (
                        f"BrokerTimeoutError: server(s) {sorted(servers)} "
                        f"did not respond within {int(timeout_ms)}ms "
                        f"({len(unit.names or [])} segments abandoned)")})
            self._metrics.add_meter("deadline_expired")
        fut_map.clear()

        if offline_key is not None and offline_results \
                and not offline_failed[0]:
            # complete, clean offline side: reusable until the offline
            # epoch moves (a retry-salvaged round is conservatively NOT
            # cached — offline_failed stays set once any entry failed).
            # Server-level stats ride along so a cache-served response
            # reports the same pruning counts as an uncached run.
            merged_stats = None
            if offline_stats:
                from pinot_tpu.query.results import ExecutionStats
                merged_stats = ExecutionStats()
                for s in offline_stats:
                    merged_stats.merge(s)
            self.result_cache.put_offline_partial(*offline_key,
                                                  offline_results,
                                                  stats=merged_stats)

        self._phase("reduce", ctx.table)
        with tracing.Scope("BrokerReduce", servers=responded):
            resp = reduce_results(ctx, results)
        for extra in server_stats:
            resp.stats.merge(extra)
        resp.exceptions = exceptions
        # any exception here means data went missing (timeout, dead
        # server, lost segments) or a server answered with an error —
        # either way the merged answer is not the whole answer
        resp.partial_result = bool(exceptions)
        resp.num_servers_queried = len(attempted)
        resp.num_servers_responded = responded
        resp.time_used_ms = (time.time() - start) * 1000.0
        if cache_key is not None:
            # put() itself refuses partial/errored responses. Hedged and
            # retry-salvaged rounds land queried != responded, which the
            # gate also refuses — DELIBERATELY: a repeat of that query
            # must re-exercise the slow/dead server, not replay a cached
            # answer past it (same failover-semantics rule as PR 1).
            self.result_cache.put(*cache_key, resp)
        return resp


def _mse_tables(parsed) -> set:
    """All physical table names an MSE query tree reads (from items,
    joins, subqueries, set operands) — the quota surface."""
    out: set = set()

    def walk(q):
        if q is None:
            return
        for attr in ("left", "right"):  # MseSetQuery operands
            walk(getattr(q, attr, None))
        fi = getattr(q, "from_item", None)
        if fi is not None:
            if getattr(fi, "table", None):
                out.add(fi.table)
            walk(getattr(fi, "subquery", None))
        for j in getattr(q, "joins", []) or []:
            item = getattr(j, "item", None) or getattr(j, "from_item", None)
            if item is not None:
                if getattr(item, "table", None):
                    out.add(item.table)
                walk(getattr(item, "subquery", None))

    walk(parsed)
    return out


def _error_response(code: int, message: str, start: float) -> BrokerResponse:
    resp = BrokerResponse()
    resp.exceptions = [{"errorCode": code, "message": message}]
    resp.time_used_ms = (time.time() - start) * 1000.0
    return resp


class StreamingMixin:
    """Per-block streaming consumption for selection queries (ref
    transport/grpc streaming + core/query/reduce/StreamingReduceService):
    server frames deserialize incrementally and row collection stops at
    OFFSET+LIMIT (remaining frames drain undecoded to keep the channel
    clean). Aggregations/group-bys fall back to the buffered path — their
    reduce needs all partials anyway."""

    def handle_streaming(self, sql: str) -> BrokerResponse:
        start = time.time()
        try:
            ctx = QueryContext.from_sql(sql)
        except (SqlParseError, ValueError):
            # joins/subqueries: same MSE delegation as the buffered path
            return self.handle(sql)
        if ctx.aggregations or ctx.group_by or ctx.distinct \
                or ctx.order_by \
                or ctx.options.get("useMultistageEngine",
                                   "").lower() == "true":
            return self.handle(sql)
        quota_reason = self._check_quota(ctx.table)
        if quota_reason:
            return _error_response(
                errorcodes.QUOTA_EXCEEDED,
                f"QuotaExceededError: {quota_reason}", start)
        route = self.routing.get_route(ctx.table)
        if route is None:
            return _error_response(
                errorcodes.TABLE_DOES_NOT_EXIST,
                f"TableDoesNotExistError: {ctx.table}", start)
        plan = route.route(ctx, unhealthy=self.failure_detector
                           .unhealthy_servers())
        request_id = self._next_id()
        needed = ctx.offset + ctx.limit
        results, exceptions, extra_stats = [], [], []
        rows_seen = 0
        blocks = 0
        for server, physical_table, names, extra in plan:
            conn = self.connections.get(server)
            if conn is None:
                exceptions.append(
                    {"errorCode": errorcodes.SERVER_ERROR,
                     "message": f"ServerNotConnected: {server}"})
                continue
            if self._selector is not None:
                self._selector.record_start(server)
            t0 = time.time()
            try:
                for frame in conn.request_streaming(
                        physical_table, sql, names, request_id, extra):
                    blocks += 1
                    if rows_seen >= needed:
                        continue  # drain to EOS, skip decoding
                    server_results, server_exc, stats = \
                        datatable.deserialize_results(frame)
                    exceptions.extend(server_exc)
                    if stats is not None:
                        extra_stats.append(stats)
                    for r in server_results:
                        results.append(r)
                        rows_seen += len(getattr(r, "rows", []))
                self.failure_detector.mark_success(server)
            except Exception as e:  # noqa: BLE001
                self.failure_detector.mark_failure(server)
                exceptions.append({"errorCode": errorcodes.SERVER_ERROR,
                                   "message": f"ServerError: {e}"})
            finally:
                if self._selector is not None:
                    self._selector.record_end(server, time.time() - t0)
        resp = reduce_results(ctx, results)
        for s in extra_stats:
            resp.stats.merge(s)
        resp.exceptions = exceptions
        resp.num_servers_queried = len(plan)
        resp.num_servers_responded = len(plan) - sum(
            1 for e in exceptions if "ServerError" in e.get("message", ""))
        resp.time_used_ms = (time.time() - start) * 1000.0
        resp.num_streamed_blocks = blocks
        return resp


class StreamingBrokerRequestHandler(StreamingMixin, BrokerRequestHandler):
    """BrokerRequestHandler + the streaming response plane."""
