"""Broker routing: which server executes which segments.

Reference parity: pinot-broker routing/ — BrokerRoutingManager.java:100
(segment preselect -> select -> prune -> instance select), instance
selectors (BalancedInstanceSelector, ReplicaGroupInstanceSelector),
segment pruners (partition, time), TimeBoundaryManager.java:56 for hybrid
tables.
"""
from __future__ import annotations

import hashlib
import itertools
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expression, Function, Identifier, Literal


#: never-repeating suffix for epochs computed during a torn (concurrently
#: mutated) segment-set iteration — see RoutingTable.epoch()
_torn_epochs = itertools.count(1)


@dataclass
class SegmentInfo:
    name: str
    servers: List[str]                       # replicas holding this segment
    partition_id: Optional[int] = None       # for partition pruning
    partition_column: Optional[str] = None
    num_partitions: int = 0
    start_time: Optional[int] = None         # time-range pruning
    end_time: Optional[int] = None
    #: segment content version (CRC); feeds the routing epoch so a
    #: replace-by-name invalidates broker result-cache entries
    version: int = 0


class _ObservedSegments(dict):
    """Segment dict that bumps its owner's mutation counter on EVERY
    mutating operation. The routing mutation API is direct dict
    assignment (roles.py rebuild, mini.py add/remove), so memoizing
    epoch() safely requires the invalidation hook to live in the dict
    itself — every mutation site is covered by construction, including
    future ones."""

    __slots__ = ("_route",)

    def __init__(self, route: "TableRoute", *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._route = route

    def _bump(self):
        # next() on itertools.count is atomic at the C level; a plain
        # `+= 1` is load/add/store and can LOSE an increment when two
        # threads mutate concurrently (routing mutators take no lock),
        # leaving the epoch memo valid for a set it no longer matches.
        # Racing bumps may store out of order — the worst case is a
        # spurious recompute, never a stale memo (the memo is only kept
        # while token == current counter).
        self._route.mutation_version = next(self._route._mut_counter)

    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        self._bump()

    def __delitem__(self, k):
        super().__delitem__(k)
        self._bump()

    def pop(self, *args):
        try:
            return super().pop(*args)
        finally:
            self._bump()

    def popitem(self):
        try:
            return super().popitem()
        finally:
            self._bump()

    def clear(self):
        super().clear()
        self._bump()

    def update(self, *args, **kwargs):
        super().update(*args, **kwargs)
        self._bump()

    def __ior__(self, other):
        # dict.__ior__ would mutate in place WITHOUT going through
        # update() — the one hole in "every mutation site is covered"
        self.update(other)
        return self

    def setdefault(self, k, default=None):
        try:
            return super().setdefault(k, default)
        finally:
            self._bump()


@dataclass
class TableRoute:
    """Routing state for one physical table (OFFLINE or REALTIME)."""
    table_name: str
    segments: Dict[str, SegmentInfo] = field(default_factory=dict)
    time_column: Optional[str] = None
    #: bumped by _ObservedSegments on every segment-dict mutation; the
    #: epoch memo keys on it (counter read/compare is GIL-atomic)
    mutation_version: int = 0

    def __post_init__(self):
        self._mut_counter = itertools.count(self.mutation_version + 1)
        if not isinstance(self.segments, _ObservedSegments):
            self.segments = _ObservedSegments(self, self.segments)


class RoutingTable:
    """segment->servers map + instance selection for one logical table."""

    def __init__(self, offline: Optional[TableRoute] = None,
                 realtime: Optional[TableRoute] = None,
                 time_boundary: Optional[int] = None,
                 selector=None):
        self.offline = offline
        self.realtime = realtime
        #: hybrid split: offline serves time <= boundary, realtime the rest
        #: (ref TimeBoundaryManager.java:56)
        self.time_boundary = time_boundary
        #: optional AdaptiveServerSelector (broker/adaptive.py) — when
        #: set, replica choice prefers low-latency/low-in-flight servers
        #: (ref routing/adaptiveserverselector/); None = round-robin
        self.selector = selector
        self._rr = 0
        self._lock = threading.Lock()
        #: memoized epochs: validity-token tuple -> epoch string. One
        #: entry per side-selection ('both' and 'offline' cache
        #: independently); pins the route objects it hashed so id() reuse
        #: after gc can never alias a stale memo.
        self._epoch_memo: Dict[str, tuple] = {}
        #: number of actual O(#segments) hash passes (test observability
        #: for the memoization contract)
        self.epoch_computes = 0

    @property
    def has_realtime(self) -> bool:
        return self.realtime is not None and bool(self.realtime.segments)

    def epoch(self) -> str:
        """Content hash of the result-affecting routing state: per-side
        segment sets with their versions, plus the hybrid time boundary.
        Any segment add / replace (version change) / remove or boundary
        move yields a new epoch, which is how the broker result cache
        invalidates — stale entries stop being addressable (no explicit
        purge fan-out, TTL + LRU reclaim the bytes). Replica placement is
        deliberately EXCLUDED: moving a segment between servers does not
        change query results.

        MEMOIZED: the O(#segments) hash runs once per segment-set
        mutation, not once per cacheable query — `TableRoute.segments` is
        an observing dict that bumps `mutation_version` at every mutation
        site, and the memo is keyed on (route identity, mutation_version,
        time_boundary). Mutating a SegmentInfo IN PLACE does not move the
        counter; routing rebuilds always swap whole SegmentInfo objects.
        """
        return self._memoized_epoch("both", (self.offline, self.realtime))

    def offline_epoch(self) -> str:
        """Epoch of ONLY the offline side (+ time boundary, which shapes
        the offline extra filter). Key for hybrid-table offline-partial
        caching: realtime appends/commits don't move it, so the offline
        partial stays addressable while the consuming side re-executes."""
        return self._memoized_epoch("offline", (self.offline,))

    def offline_segments_for(self, ctx: QueryContext) -> set:
        """Names of offline segments a COMPLETE plan for `ctx` must
        cover (everything routing wouldn't prune). Callers caching the
        offline partial compare this against what the plan actually
        placed: a segment with no live replica is silently dropped by
        _route_physical, and placement is deliberately outside the
        epoch, so coverage must be checked separately."""
        if self.offline is None:
            return set()
        return {s.name for s in self.offline.segments.values()
                if not _prunable(s, ctx)}

    def prunes_to_zero(self, ctx: QueryContext) -> bool:
        """True when routing would select NO segment for `ctx` purely by
        pruning (or the table is empty) — the negative-cache gate. A
        segment dropped because no replica is placeable does NOT count:
        placement is outside the epoch, so caching that empty answer
        would outlive the outage."""
        for side in (self.offline, self.realtime):
            if side is None:
                continue
            for seg in side.segments.values():
                if not _prunable(seg, ctx):
                    return False
        return True

    def _memoized_epoch(self, which: str, sides: tuple) -> str:
        # identity + mutation counter, never TableRoute.__eq__ (a
        # dataclass eq would walk the whole segment dict — the exact
        # O(#segments) cost being memoized away). The memo entry pins the
        # route objects it hashed, so an id() can't be reused for a
        # different live route while its memo is current.
        token = (tuple(id(s) if s is not None else None for s in sides),
                 tuple(s.mutation_version if s is not None else -1
                       for s in sides),
                 self.time_boundary)
        memo = self._epoch_memo.get(which)
        if memo is not None and memo[0] == token:
            return memo[2]
        value = self._compute_epoch(sides)
        if not value.startswith("<torn:"):
            # torn epochs never repeat by design — memoizing one would
            # repeat it; tuple assignment is atomic under the GIL
            self._epoch_memo[which] = (token, sides, value)
        return value

    def _compute_epoch(self, sides: tuple) -> str:
        """Reads race segment-set mutation (routing mutators don't lock
        the dicts — same read-mostly convention as route()); a torn
        iteration returns a never-repeating epoch, degrading that one
        query to a cache miss instead of failing it."""
        self.epoch_computes += 1
        for _ in range(3):
            try:
                h = hashlib.sha1()
                for side in sides:
                    if side is None:
                        h.update(b"<none>\0")
                        continue
                    h.update(side.table_name.encode())
                    h.update(b"\0")
                    for name in sorted(side.segments):
                        info = side.segments.get(name)
                        if info is None:
                            raise RuntimeError("segment set changed")
                        # NUL-delimited fields: names routinely end in
                        # digits, so 'day_1'+'2345' must not hash like
                        # 'day_12'+'345'
                        h.update(name.encode())
                        h.update(b"\0")
                        h.update(str(info.version).encode())
                        h.update(b"\0")
                h.update(str(self.time_boundary).encode())
                return h.hexdigest()
            except RuntimeError:  # dict resized mid-iteration
                continue
        return f"<torn:{id(self)}:{next(_torn_epochs)}>"

    def route(self, ctx: QueryContext, unhealthy: Optional[Set[str]] = None
              ) -> List[Tuple[str, str, List[str], Optional[str]]]:
        """Returns [(server, physical_table, segment_names, extra_filter)].

        extra_filter is the time-boundary predicate SQL fragment to AND in
        (the reference rewrites the query per physical table the same way).
        unhealthy: servers the failure detector wants skipped — a segment
        whose replicas are ALL unhealthy still routes (partial answers
        beat silently dropped segments, matching the reference's fallback
        when the selector exhausts candidates).
        """
        out: List[Tuple[str, str, List[str], Optional[str]]] = []
        if self.offline is not None:
            extra = None
            if self.realtime is not None and self.time_boundary is not None \
                    and self.offline.time_column:
                extra = f"{self.offline.time_column} <= {self.time_boundary}"
            out.extend(self._route_physical(self.offline, ctx, extra,
                                            unhealthy or set()))
        if self.realtime is not None:
            extra = None
            if self.offline is not None and self.time_boundary is not None \
                    and self.realtime.time_column:
                extra = f"{self.realtime.time_column} > {self.time_boundary}"
            out.extend(self._route_physical(self.realtime, ctx, extra,
                                            unhealthy or set()))
        return out

    # ------------------------------------------------------------------
    def _route_physical(self, route: TableRoute, ctx: QueryContext,
                        extra_filter: Optional[str], unhealthy: Set[str]):
        selected = [s for s in route.segments.values()
                    if not _prunable(s, ctx)]
        per_server: Dict[str, List[str]] = {}
        with self._lock:
            for seg in selected:
                if self.selector is not None:
                    server = self.selector.pick(seg.servers, unhealthy,
                                                self._rr)
                    if server is None:  # all unhealthy: any replica
                        server = _pick_replica(seg.servers, self._rr,
                                               unhealthy)
                else:
                    server = _pick_replica(seg.servers, self._rr, unhealthy)
                if server is None:
                    continue
                per_server.setdefault(server, []).append(seg.name)
            self._rr += 1
        return [(server, route.table_name, names, extra_filter)
                for server, names in per_server.items()]

    def reroute_segments(self, physical_table: str, segment_names: List[str],
                         exclude: Set[str], extra_filter: Optional[str]):
        """Re-place segments on surviving replicas after a server failed
        mid-query (ref QueryRouter retry on unhealthy server). Returns
        (entries, unplaced_segment_names) — unplaced segments have NO
        surviving replica and must surface as an error, never silently
        vanish from the answer."""
        route = None
        for r in (self.offline, self.realtime):
            if r is not None and r.table_name == physical_table:
                route = r
                break
        if route is None:
            return [], list(segment_names)
        per_server: Dict[str, List[str]] = {}
        unplaced: List[str] = []
        with self._lock:
            for name in segment_names:
                seg = route.segments.get(name)
                if seg is None:
                    unplaced.append(name)
                    continue
                server = _pick_replica(seg.servers, self._rr, exclude,
                                       strict=True)
                if server is None:
                    unplaced.append(name)
                    continue
                per_server.setdefault(server, []).append(seg.name)
            self._rr += 1
        return ([(server, physical_table, names, extra_filter)
                 for server, names in per_server.items()], unplaced)


def _pick_replica(servers: List[str], rr: int, skip: Set[str],
                  strict: bool = False) -> Optional[str]:
    """Balanced selection over healthy replicas (ref
    BalancedInstanceSelector); falls back to ANY replica when all are
    marked unhealthy — unless strict (mid-query retry must not resend to
    the server that just failed)."""
    if not servers:
        return None
    healthy = [s for s in servers if s not in skip]
    if healthy:
        return healthy[rr % len(healthy)]
    if strict:
        return None
    return servers[rr % len(servers)]


def _prunable(seg: SegmentInfo, ctx: QueryContext) -> bool:
    """Partition pruning (ref broker/routing/segmentpruner/): a segment can
    be skipped when an EQ filter on the partition column hashes to a
    different partition."""
    if ctx.filter is None or seg.partition_column is None or not seg.num_partitions:
        return False
    value = _eq_value(ctx.filter, seg.partition_column)
    if value is None:
        return False
    p = _modulo_partition(value, seg.num_partitions)
    if p is None:  # non-numeric value: cannot prove mismatch, keep segment
        return False
    return p != seg.partition_id


def _eq_value(expr: Expression, column: str):
    """Value of a top-level (AND-reachable) EQ predicate on `column`."""
    if not isinstance(expr, Function):
        return None
    if expr.name == "and":
        for a in expr.args:
            v = _eq_value(a, column)
            if v is not None:
                return v
        return None
    if expr.name == "equals" and expr.args \
            and isinstance(expr.args[0], Identifier) \
            and expr.args[0].name == column \
            and isinstance(expr.args[1], Literal):
        return expr.args[1].value
    return None


def _modulo_partition(value, num_partitions: int) -> Optional[int]:
    """Ref segment-spi partition/ModuloPartitionFunction — numeric-only.
    Returns None for non-numeric values: Python's salted str hash is not
    stable across processes, so using it would silently mis-prune
    (ADVICE r1 medium)."""
    try:
        return int(value) % num_partitions
    except (TypeError, ValueError):
        return None


class BrokerRoutingManager:
    """All tables' routing state (ref BrokerRoutingManager.java:100).
    Rebuilt from cluster state on assignment changes (the ExternalView
    watch analog is a callback from the controller-lite)."""

    def __init__(self, selector=None):
        self._tables: Dict[str, RoutingTable] = {}
        #: memoized single-side views for suffix-addressed queries
        #: ('tbl_OFFLINE'): a fresh wrapper per get_route would carry an
        #: empty epoch memo, re-hashing O(#segments) per query — the
        #: exact cost the epoch memoization removes
        self._suffix_views: Dict[str, RoutingTable] = {}
        #: shared AdaptiveServerSelector attached to every route
        self.selector = selector
        self._lock = threading.Lock()

    def set_route(self, logical_table: str, routing: RoutingTable) -> None:
        if routing.selector is None:
            routing.selector = self.selector
        with self._lock:
            self._tables[logical_table] = routing
            for suffix in ("_OFFLINE", "_REALTIME"):
                self._suffix_views.pop(logical_table + suffix, None)

    def get_route(self, table: str) -> Optional[RoutingTable]:
        from pinot_tpu.models import base_table_name
        base = base_table_name(table)
        with self._lock:
            rt = self._tables.get(base)
            if rt is None:
                return None
            if base == table:
                return rt
            view = self._suffix_views.get(table)
            if view is None:
                # the view SHARES the underlying TableRoute, so segment
                # mutations flow through; only the memo lives here
                view = (RoutingTable(offline=rt.offline)
                        if table.endswith("_OFFLINE")
                        else RoutingTable(realtime=rt.realtime))
                view.selector = rt.selector
                self._suffix_views[table] = view
            return view

    @property
    def table_names(self) -> List[str]:
        with self._lock:
            return list(self._tables.keys())
