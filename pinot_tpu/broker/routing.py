"""Broker routing: which server executes which segments.

Reference parity: pinot-broker routing/ — BrokerRoutingManager.java:100
(segment preselect -> select -> prune -> instance select), instance
selectors (BalancedInstanceSelector, ReplicaGroupInstanceSelector),
segment pruners (partition, time), TimeBoundaryManager.java:56 for hybrid
tables.
"""
from __future__ import annotations

import hashlib
import itertools
import random
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expression, Function, Identifier, Literal


#: never-repeating suffix for epochs computed during a torn (concurrently
#: mutated) segment-set iteration — see RoutingTable.epoch()
_torn_epochs = itertools.count(1)


@dataclass
class SegmentInfo:
    name: str
    servers: List[str]                       # replicas holding this segment
    partition_id: Optional[int] = None       # for partition pruning
    partition_column: Optional[str] = None
    num_partitions: int = 0
    start_time: Optional[int] = None         # time-range pruning
    end_time: Optional[int] = None
    #: segment content version (CRC); feeds the routing epoch so a
    #: replace-by-name invalidates broker result-cache entries
    version: int = 0


class _ObservedSegments(dict):
    """Segment dict that bumps its owner's mutation counter on EVERY
    mutating operation. The routing mutation API is direct dict
    assignment (roles.py rebuild, mini.py add/remove), so memoizing
    epoch() safely requires the invalidation hook to live in the dict
    itself — every mutation site is covered by construction, including
    future ones."""

    __slots__ = ("_route",)

    def __init__(self, route: "TableRoute", *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._route = route

    def _bump(self):
        # next() on itertools.count is atomic at the C level; a plain
        # `+= 1` is load/add/store and can LOSE an increment when two
        # threads mutate concurrently (routing mutators take no lock),
        # leaving the epoch memo valid for a set it no longer matches.
        # Racing bumps may store out of order — the worst case is a
        # spurious recompute, never a stale memo (the memo is only kept
        # while token == current counter).
        self._route.mutation_version = next(self._route._mut_counter)

    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        self._bump()

    def __delitem__(self, k):
        super().__delitem__(k)
        self._bump()

    def pop(self, *args):
        try:
            return super().pop(*args)
        finally:
            self._bump()

    def popitem(self):
        try:
            return super().popitem()
        finally:
            self._bump()

    def clear(self):
        super().clear()
        self._bump()

    def update(self, *args, **kwargs):
        super().update(*args, **kwargs)
        self._bump()

    def __ior__(self, other):
        # dict.__ior__ would mutate in place WITHOUT going through
        # update() — the one hole in "every mutation site is covered"
        self.update(other)
        return self

    def setdefault(self, k, default=None):
        try:
            return super().setdefault(k, default)
        finally:
            self._bump()


@dataclass
class TableRoute:
    """Routing state for one physical table (OFFLINE or REALTIME)."""
    table_name: str
    segments: Dict[str, SegmentInfo] = field(default_factory=dict)
    time_column: Optional[str] = None
    #: >= 2 makes this a replica-group fault domain: each segment's
    #: `servers` list is GROUP-ORDERED (element g = the group-g replica,
    #: the assignment contract), and the broker scatters each query to
    #: ONE group instead of round-robin across replicas
    num_replica_groups: int = 0
    #: bumped by _ObservedSegments on every segment-dict mutation; the
    #: epoch memo keys on it (counter read/compare is GIL-atomic)
    mutation_version: int = 0

    def __post_init__(self):
        self._mut_counter = itertools.count(self.mutation_version + 1)
        if not isinstance(self.segments, _ObservedSegments):
            self.segments = _ObservedSegments(self, self.segments)


class ReplicaGroupInstanceSelector:
    """Pick ONE replica group per query (ref
    routing/instanceselector/ReplicaGroupInstanceSelector.java): every
    segment of the query scatters to the same group, so a query touches
    one fault domain — and a whole-group loss is survivable by
    re-scattering onto another group, which balanced routing cannot
    express.

    Choice discipline, in order:

      1. health — only groups with NO unhealthy member are candidates
         (one dead member would fail part of the scatter; the caller
         falls back to per-segment balanced selection when every group
         is degraded).
      2. stickiness — a query fingerprint maps to the group that served
         it before (bounded LRU): per-segment partial caches and HBM
         residency live on the servers that executed the plan, so
         repeats must land on the same machines to hit them.
      3. adaptive latency — for new fingerprints, the group whose
         WORST member scores best (the scatter waits for its slowest
         member) via the shared AdaptiveServerSelector.
      4. residency — on ties, the group whose members advertise the
         most HBM-resident bytes for the query's table (instance-sweep
         heartbeat hints, `update_residency`).
      5. round-robin over remaining ties.
    """

    def __init__(self, adaptive=None, sticky_max: int = 4096):
        self.adaptive = adaptive
        self.sticky_max = int(sticky_max)
        #: (physical table, query fingerprint) -> group index
        self._sticky: "OrderedDict[tuple, int]" = OrderedDict()
        #: server -> {physical table: HBM-resident bytes}
        self._residency: Dict[str, Dict[str, int]] = {}
        self._rr = 0
        self._lock = threading.Lock()

    # -- instance-sweep feeds ------------------------------------------
    def update_residency(self, server: str,
                         table_bytes: Dict[str, int]) -> None:
        """Heartbeat payload: per-table resident bytes one server
        advertises (cluster/roles.py plumbs this from the coordinator's
        instance sweep)."""
        with self._lock:
            self._residency[server] = dict(table_bytes or {})

    def residency_bytes(self, members: Sequence[str], table: str) -> int:
        with self._lock:
            return sum(self._residency.get(m, {}).get(table, 0)
                       for m in members)

    # -- selection ------------------------------------------------------
    def pick_group(self, physical_table: str,
                   groups: Sequence[Sequence[str]],
                   unhealthy: Set[str],
                   fingerprint: Optional[str] = None) -> Optional[int]:
        """Index of the group this query scatters to, or None when no
        group is fully healthy (caller degrades to per-segment
        selection). Sticky entries are dropped the moment their group
        stops being healthy — demotion, not just avoidance, so the next
        repeat re-evaluates instead of bouncing off the dead group."""
        healthy = [g for g, members in enumerate(groups)
                   if members and not (set(members) & unhealthy)]
        if not healthy:
            return None
        key = None
        if fingerprint is not None:
            key = (physical_table, fingerprint)
            with self._lock:
                g = self._sticky.get(key)
                if g is not None:
                    if g in healthy:
                        self._sticky.move_to_end(key)
                        return g
                    del self._sticky[key]  # demoted group: unstick
        if len(healthy) == 1:
            g = healthy[0]
        else:
            scored = []
            for g in healthy:
                # the scatter completes when the SLOWEST member answers,
                # so a group is as good as its worst server
                worst = (max(self.adaptive.score(s) for s in groups[g])
                         if self.adaptive is not None else 0.0)
                res = self.residency_bytes(groups[g], physical_table)
                scored.append((worst, -res, g))
            scored.sort()
            ties = [g for w, r, g in scored
                    if (w, r) == (scored[0][0], scored[0][1])]
            with self._lock:
                g = ties[self._rr % len(ties)]
                self._rr += 1
        if key is not None:
            with self._lock:
                self._sticky[key] = g
                self._sticky.move_to_end(key)
                while len(self._sticky) > self.sticky_max:
                    self._sticky.popitem(last=False)
        return g


def _derive_groups(segments: Sequence[SegmentInfo],
                   num_groups: int) -> List[List[str]]:
    """Group membership recovered from the assignment contract: a
    segment's server list is group-ordered, so column g over all
    segments IS group g. No separate group map can drift from the
    placements actually in effect."""
    groups: List[set] = [set() for _ in range(num_groups)]
    for seg in segments:
        for g in range(min(num_groups, len(seg.servers))):
            groups[g].add(seg.servers[g])
    return [sorted(g) for g in groups]


class RoutingTable:
    """segment->servers map + instance selection for one logical table."""

    def __init__(self, offline: Optional[TableRoute] = None,
                 realtime: Optional[TableRoute] = None,
                 time_boundary: Optional[int] = None,
                 selector=None, group_selector=None):
        self.offline = offline
        self.realtime = realtime
        #: hybrid split: offline serves time <= boundary, realtime the rest
        #: (ref TimeBoundaryManager.java:56)
        self.time_boundary = time_boundary
        #: optional AdaptiveServerSelector (broker/adaptive.py) — when
        #: set, replica choice prefers low-latency/low-in-flight servers
        #: (ref routing/adaptiveserverselector/); None = round-robin
        self.selector = selector
        #: ReplicaGroupInstanceSelector used for sides with
        #: num_replica_groups >= 2 (one group per query); None falls
        #: back to per-segment selection even for grouped tables
        self.group_selector = group_selector
        self._rr = 0
        self._lock = threading.Lock()
        #: memoized epochs: validity-token tuple -> epoch string. One
        #: entry per side-selection ('both' and 'offline' cache
        #: independently); pins the route objects it hashed so id() reuse
        #: after gc can never alias a stale memo.
        self._epoch_memo: Dict[str, tuple] = {}
        #: number of actual O(#segments) hash passes (test observability
        #: for the memoization contract)
        self.epoch_computes = 0

    @property
    def has_realtime(self) -> bool:
        return self.realtime is not None and bool(self.realtime.segments)

    def epoch(self) -> str:
        """Content hash of the result-affecting routing state: per-side
        segment sets with their versions, plus the hybrid time boundary.
        Any segment add / replace (version change) / remove or boundary
        move yields a new epoch, which is how the broker result cache
        invalidates — stale entries stop being addressable (no explicit
        purge fan-out, TTL + LRU reclaim the bytes). Replica placement is
        deliberately EXCLUDED: moving a segment between servers does not
        change query results.

        MEMOIZED: the O(#segments) hash runs once per segment-set
        mutation, not once per cacheable query — `TableRoute.segments` is
        an observing dict that bumps `mutation_version` at every mutation
        site, and the memo is keyed on (route identity, mutation_version,
        time_boundary). Mutating a SegmentInfo IN PLACE does not move the
        counter; routing rebuilds always swap whole SegmentInfo objects.
        """
        return self._memoized_epoch("both", (self.offline, self.realtime))

    def offline_epoch(self) -> str:
        """Epoch of ONLY the offline side (+ time boundary, which shapes
        the offline extra filter). Key for hybrid-table offline-partial
        caching: realtime appends/commits don't move it, so the offline
        partial stays addressable while the consuming side re-executes."""
        return self._memoized_epoch("offline", (self.offline,))

    def offline_segments_for(self, ctx: QueryContext) -> set:
        """Names of offline segments a COMPLETE plan for `ctx` must
        cover (everything routing wouldn't prune). Callers caching the
        offline partial compare this against what the plan actually
        placed: a segment with no live replica is silently dropped by
        _route_physical, and placement is deliberately outside the
        epoch, so coverage must be checked separately."""
        if self.offline is None:
            return set()
        return {s.name for s in self.offline.segments.values()
                if not _prunable(s, ctx)}

    def prunes_to_zero(self, ctx: QueryContext) -> bool:
        """True when routing would select NO segment for `ctx` purely by
        pruning (or the table is empty) — the negative-cache gate. A
        segment dropped because no replica is placeable does NOT count:
        placement is outside the epoch, so caching that empty answer
        would outlive the outage."""
        for side in (self.offline, self.realtime):
            if side is None:
                continue
            for seg in side.segments.values():
                if not _prunable(seg, ctx):
                    return False
        return True

    def _memoized_epoch(self, which: str, sides: tuple) -> str:
        # identity + mutation counter, never TableRoute.__eq__ (a
        # dataclass eq would walk the whole segment dict — the exact
        # O(#segments) cost being memoized away). The memo entry pins the
        # route objects it hashed, so an id() can't be reused for a
        # different live route while its memo is current.
        token = (tuple(id(s) if s is not None else None for s in sides),
                 tuple(s.mutation_version if s is not None else -1
                       for s in sides),
                 self.time_boundary)
        memo = self._epoch_memo.get(which)
        if memo is not None and memo[0] == token:
            return memo[2]
        value = self._compute_epoch(sides)
        if not value.startswith("<torn:"):
            # torn epochs never repeat by design — memoizing one would
            # repeat it; tuple assignment is atomic under the GIL
            self._epoch_memo[which] = (token, sides, value)
        return value

    def _compute_epoch(self, sides: tuple) -> str:
        """Reads race segment-set mutation (routing mutators don't lock
        the dicts — same read-mostly convention as route()); a torn
        iteration returns a never-repeating epoch, degrading that one
        query to a cache miss instead of failing it."""
        self.epoch_computes += 1
        for _ in range(3):
            try:
                h = hashlib.sha1()
                for side in sides:
                    if side is None:
                        h.update(b"<none>\0")
                        continue
                    h.update(side.table_name.encode())
                    h.update(b"\0")
                    for name in sorted(side.segments):
                        info = side.segments.get(name)
                        if info is None:
                            raise RuntimeError("segment set changed")
                        # NUL-delimited fields: names routinely end in
                        # digits, so 'day_1'+'2345' must not hash like
                        # 'day_12'+'345'
                        h.update(name.encode())
                        h.update(b"\0")
                        h.update(str(info.version).encode())
                        h.update(b"\0")
                h.update(str(self.time_boundary).encode())
                return h.hexdigest()
            except RuntimeError:  # dict resized mid-iteration
                continue
        return f"<torn:{id(self)}:{next(_torn_epochs)}>"

    def route(self, ctx: QueryContext, unhealthy: Optional[Set[str]] = None
              ) -> List[Tuple[str, str, List[str], Optional[str]]]:
        """Returns [(server, physical_table, segment_names, extra_filter)].

        extra_filter is the time-boundary predicate SQL fragment to AND in
        (the reference rewrites the query per physical table the same way).
        unhealthy: servers the failure detector wants skipped — a segment
        whose replicas are ALL unhealthy still routes (partial answers
        beat silently dropped segments, matching the reference's fallback
        when the selector exhausts candidates).
        """
        out: List[Tuple[str, str, List[str], Optional[str]]] = []
        if self.offline is not None:
            extra = None
            if self.realtime is not None and self.time_boundary is not None \
                    and self.offline.time_column:
                extra = f"{self.offline.time_column} <= {self.time_boundary}"
            out.extend(self._route_physical(self.offline, ctx, extra,
                                            unhealthy or set()))
        if self.realtime is not None:
            extra = None
            if self.offline is not None and self.time_boundary is not None \
                    and self.realtime.time_column:
                extra = f"{self.realtime.time_column} > {self.time_boundary}"
            out.extend(self._route_physical(self.realtime, ctx, extra,
                                            unhealthy or set()))
        return out

    # ------------------------------------------------------------------
    def _route_physical(self, route: TableRoute, ctx: QueryContext,
                        extra_filter: Optional[str], unhealthy: Set[str]):
        selected = [s for s in route.segments.values()
                    if not _prunable(s, ctx)]
        if route.num_replica_groups >= 2 and self.group_selector is not None \
                and selected:
            entries = self._route_one_group(route, ctx, selected,
                                            extra_filter, unhealthy)
            if entries is not None:
                return entries
            # no fully-healthy group: degrade to per-segment selection
            # below — known-dead servers are skipped segment by segment,
            # which beats scattering part of the query at a corpse
        per_server: Dict[str, List[str]] = {}
        with self._lock:
            for seg in selected:
                if self.selector is not None:
                    server = self.selector.pick(seg.servers, unhealthy,
                                                self._rr)
                    if server is None:  # all unhealthy: any replica
                        server = _pick_replica(seg.servers, self._rr,
                                               unhealthy)
                else:
                    server = _pick_replica(seg.servers, self._rr, unhealthy)
                if server is None:
                    continue
                per_server.setdefault(server, []).append(seg.name)
            self._rr += 1
        return [(server, route.table_name, names, extra_filter)
                for server, names in per_server.items()]

    def _route_one_group(self, route: TableRoute, ctx: QueryContext,
                         selected: List[SegmentInfo],
                         extra_filter: Optional[str],
                         unhealthy: Set[str]):
        """Scatter the WHOLE query to one replica group (the fault-domain
        contract). None when no group is fully healthy."""
        groups = _derive_groups(selected, route.num_replica_groups)
        g = self.group_selector.pick_group(
            route.table_name, groups, unhealthy,
            fingerprint=ctx.fingerprint())
        if g is None:
            return None
        per_server: Dict[str, List[str]] = {}
        with self._lock:
            for seg in selected:
                if g < len(seg.servers):
                    server = seg.servers[g]
                    if server in unhealthy:
                        # stale group view (segment set mutated since
                        # health check): place on any healthy replica
                        server = _pick_replica(seg.servers, self._rr,
                                               unhealthy)
                else:
                    # partially-replicated segment (fewer copies than
                    # groups): fall back per segment rather than drop it
                    server = _pick_replica(seg.servers, self._rr, unhealthy)
                if server is None:
                    continue
                per_server.setdefault(server, []).append(seg.name)
            self._rr += 1
        return [(server, route.table_name, names, extra_filter)
                for server, names in per_server.items()]

    # -- fault-domain introspection ------------------------------------
    def _route_named(self, physical_table: str) -> Optional[TableRoute]:
        for r in (self.offline, self.realtime):
            if r is not None and r.table_name == physical_table:
                return r
        return None

    def group_peers(self, physical_table: str, server: str) -> Set[str]:
        """Every server sharing a replica-group index with `server`
        (itself included) — the demotion set when one member fails
        mid-query: the retry must avoid the WHOLE group, because sending
        the re-scatter to the dead member's healthy peers splits the
        query across fault domains and a second loss in either would
        fail it. Empty for non-grouped tables."""
        route = self._route_named(physical_table)
        if route is None or route.num_replica_groups < 2:
            return set()
        positions = {i for seg in route.segments.values()
                     for i, s in enumerate(seg.servers) if s == server}
        if not positions:
            return set()
        return {seg.servers[i] for seg in route.segments.values()
                for i in positions if i < len(seg.servers)}

    def group_index_of(self, physical_table: str,
                       server: str) -> Optional[int]:
        """The replica-group index `server` serves for this table (its
        lowest position across segment replica lists) — failpoint/test
        observability for group-scoped chaos. None when ungrouped or
        unknown."""
        route = self._route_named(physical_table)
        if route is None or route.num_replica_groups < 2:
            return None
        positions = [i for seg in route.segments.values()
                     for i, s in enumerate(seg.servers) if s == server]
        return min(positions) if positions else None

    def reroute_segments(self, physical_table: str, segment_names: List[str],
                         exclude: Set[str], extra_filter: Optional[str]):
        """Re-place segments on surviving replicas after a server failed
        mid-query (ref QueryRouter retry on unhealthy server). Returns
        (entries, unplaced_segment_names) — unplaced segments have NO
        surviving replica and must surface as an error, never silently
        vanish from the answer. For replica-group tables the shared rr
        index makes the re-placement CONVERGE: excluding the demoted
        group leaves every segment's surviving replicas in the same
        group order, so one rr value lands all of them on one surviving
        group."""
        route = self._route_named(physical_table)
        if route is None:
            return [], list(segment_names)
        per_server: Dict[str, List[str]] = {}
        unplaced: List[str] = []
        with self._lock:
            for name in segment_names:
                seg = route.segments.get(name)
                if seg is None:
                    unplaced.append(name)
                    continue
                server = _pick_replica(seg.servers, self._rr, exclude,
                                       strict=True)
                if server is None:
                    unplaced.append(name)
                    continue
                per_server.setdefault(server, []).append(seg.name)
            self._rr += 1
        return ([(server, physical_table, names, extra_filter)
                 for server, names in per_server.items()], unplaced)


def _pick_replica(servers: List[str], rr: int, skip: Set[str],
                  strict: bool = False) -> Optional[str]:
    """Balanced selection over healthy replicas (ref
    BalancedInstanceSelector); falls back to ANY replica when all are
    marked unhealthy — unless strict (mid-query retry must not resend to
    the server that just failed)."""
    if not servers:
        return None
    healthy = [s for s in servers if s not in skip]
    if healthy:
        return healthy[rr % len(healthy)]
    if strict:
        return None
    return servers[rr % len(servers)]


def _prunable(seg: SegmentInfo, ctx: QueryContext) -> bool:
    """Partition pruning (ref broker/routing/segmentpruner/): a segment
    can be skipped when an EQ/IN filter on the partition column proves
    EVERY matching row hashes to a different partition."""
    if ctx.filter is None or seg.partition_column is None or not seg.num_partitions:
        return False
    values = _partition_values(ctx.filter, seg.partition_column)
    if not values:
        return False
    for value in values:
        p = _modulo_partition(value, seg.num_partitions)
        if p is None:  # non-numeric value: cannot prove mismatch, keep
            return False
        if p == seg.partition_id:
            return False
    return True


def _partition_values(expr: Expression, column: str) -> Optional[list]:
    """Literal values a top-level (AND-reachable) EQ or IN predicate on
    `column` restricts rows to — the partition-pruning surface. None
    when no such predicate constrains the column (or an IN carries a
    non-literal operand, which makes the value set unprovable)."""
    if not isinstance(expr, Function):
        return None
    if expr.name == "and":
        for a in expr.args:
            v = _partition_values(a, column)
            if v is not None:
                return v
        return None
    if not expr.args or not isinstance(expr.args[0], Identifier) \
            or expr.args[0].name != column:
        return None
    if expr.name == "equals" and len(expr.args) == 2 \
            and isinstance(expr.args[1], Literal):
        return [expr.args[1].value]
    if expr.name == "in" and len(expr.args) >= 2:
        if all(isinstance(a, Literal) for a in expr.args[1:]):
            return [a.value for a in expr.args[1:]]
    return None


def _modulo_partition(value, num_partitions: int) -> Optional[int]:
    """Ref segment-spi partition/ModuloPartitionFunction — numeric-only.
    Returns None for non-numeric values: Python's salted str hash is not
    stable across processes, so using it would silently mis-prune
    (ADVICE r1 medium)."""
    try:
        return int(value) % num_partitions
    except (TypeError, ValueError):
        return None


class BrokerRoutingManager:
    """All tables' routing state (ref BrokerRoutingManager.java:100).
    Rebuilt from cluster state on assignment changes (the ExternalView
    watch analog is a callback from the controller-lite)."""

    def __init__(self, selector=None, group_selector=None):
        self._tables: Dict[str, RoutingTable] = {}
        #: memoized single-side views for suffix-addressed queries
        #: ('tbl_OFFLINE'): a fresh wrapper per get_route would carry an
        #: empty epoch memo, re-hashing O(#segments) per query — the
        #: exact cost the epoch memoization removes
        self._suffix_views: Dict[str, RoutingTable] = {}
        #: shared AdaptiveServerSelector attached to every route
        self.selector = selector
        #: shared ReplicaGroupInstanceSelector: one per broker, so
        #: fingerprint stickiness and residency hints span all tables
        self.group_selector = (ReplicaGroupInstanceSelector(adaptive=selector)
                               if group_selector is None else group_selector)
        self._lock = threading.Lock()

    def set_route(self, logical_table: str, routing: RoutingTable) -> None:
        if routing.selector is None:
            routing.selector = self.selector
        if routing.group_selector is None:
            routing.group_selector = self.group_selector
        with self._lock:
            self._tables[logical_table] = routing
            for suffix in ("_OFFLINE", "_REALTIME"):
                self._suffix_views.pop(logical_table + suffix, None)

    def get_route(self, table: str) -> Optional[RoutingTable]:
        from pinot_tpu.models import base_table_name
        base = base_table_name(table)
        with self._lock:
            rt = self._tables.get(base)
            if rt is None:
                return None
            if base == table:
                return rt
            view = self._suffix_views.get(table)
            if view is None:
                # the view SHARES the underlying TableRoute, so segment
                # mutations flow through; only the memo lives here
                view = (RoutingTable(offline=rt.offline)
                        if table.endswith("_OFFLINE")
                        else RoutingTable(realtime=rt.realtime))
                view.selector = rt.selector
                view.group_selector = rt.group_selector
                self._suffix_views[table] = view
            return view

    @property
    def table_names(self) -> List[str]:
        with self._lock:
            return list(self._tables.keys())
