"""Two-tier query result caching.

Reference parity: the Druid-style split the OLAP world converged on —
broker whole-result caching (Druid `useResultLevelCache`, Pinot's broker
response cache proposals) and historical/server per-segment partial
caching (Druid `populateCache`/`useCache` on immutable segments only).
Tier 1 (`BrokerResultCache`) memoizes the final BrokerResponse keyed by
(query fingerprint, table, routing epoch); tier 2 (`SegmentResultCache`)
memoizes per-segment aggregation/group-by/distinct partials keyed by
(segment name, segment version, plan fingerprint). Both invalidate by
version, never by mutation-in-place: a segment add/replace/remove changes
the key, so stale entries simply stop being addressable and age out via
TTL + LRU byte pressure.
"""
from pinot_tpu.cache.core import CacheStats, LruTtlCache
from pinot_tpu.cache.broker_cache import BrokerResultCache
from pinot_tpu.cache.segment_cache import SegmentResultCache, segment_version

__all__ = [
    "BrokerResultCache",
    "CacheStats",
    "LruTtlCache",
    "SegmentResultCache",
    "segment_version",
]
