"""Two-tier query result caching.

Reference parity: the Druid-style split the OLAP world converged on —
broker whole-result caching (Druid `useResultLevelCache`, Pinot's broker
response cache proposals) and historical/server per-segment partial
caching (Druid `populateCache`/`useCache` on immutable segments only).
Tier 1 (`BrokerResultCache`) memoizes the final BrokerResponse keyed by
(query fingerprint, table, routing epoch); tier 2 (`SegmentResultCache`)
memoizes per-segment aggregation/group-by/distinct partials keyed by
(segment name, segment version, plan fingerprint). Both invalidate by
version, never by mutation-in-place: a segment add/replace/remove changes
the key, so stale entries simply stop being addressable and age out via
TTL + LRU byte pressure.

Distributed fabric (this PR's subsystem): a standalone cache-server role
(`cache/remote.py` CacheServer) shares one byte budget across replicas;
`RemoteCacheBackend` mounts it with pooling + timeouts + a circuit
breaker, and `TieredCache` (`cache/tiered.py`) composes the local
`LruTtlCache` as L1 with the remote tier as L2 behind the same byte
interface — selected per tier via
`pinot.broker.result.cache.backend` / `pinot.server.segment.cache.backend`
(= local | tiered). `cache/warmup.py` replays a per-table fingerprint log
against freshly loaded immutable segments so rollouts start warm.
"""
from pinot_tpu.cache.core import CacheStats, LruTtlCache
from pinot_tpu.cache.broker_cache import BrokerResultCache
from pinot_tpu.cache.remote import CacheServer, RemoteCacheBackend
from pinot_tpu.cache.segment_cache import SegmentResultCache, segment_version
from pinot_tpu.cache.tiered import TieredCache
from pinot_tpu.cache.warmup import FingerprintLog, SegmentWarmup

__all__ = [
    "BrokerResultCache",
    "CacheServer",
    "CacheStats",
    "FingerprintLog",
    "LruTtlCache",
    "RemoteCacheBackend",
    "SegmentResultCache",
    "SegmentWarmup",
    "TieredCache",
    "segment_version",
]
