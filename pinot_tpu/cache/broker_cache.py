"""Tier 1: broker whole-result cache.

Reference parity: Druid's `useResultLevelCache` — memoize the FINAL
merged response, keyed by (canonical query fingerprint, logical table,
routing epoch). The epoch is a content hash over the route's segment set
and per-segment versions (broker/routing.py `RoutingTable.epoch`), so a
segment add / replace / remove or time-boundary move changes the key and
stale entries stop being addressable — no explicit invalidation fan-out.

Tables with a realtime side are NOT cached by default: consuming
segments grow without any routing change, and a whole-result hit would
hide freshly ingested rows. `cache_realtime=True` opts in for
append-rare realtime tables that can tolerate TTL-bounded staleness.
"""
from __future__ import annotations

import itertools
from typing import Optional

from pinot_tpu.cache.core import (LruTtlCache, cache_bypassed,  # noqa: F401
                                  dumps, loads, wire_dumps_response,
                                  wire_dumps_results, wire_loads_response,
                                  wire_loads_results_stats)
from pinot_tpu.query.reduce import BrokerResponse

#: default per-instance metric label — several handlers in one process
#: (tests run multiple MiniClusters) share the 'broker' registry
_broker_ids = itertools.count(0)


def broker_remote_key(key) -> Optional[str]:
    """Tuple key -> wire key string. Epochs are content hashes of the
    segment set (never torn ones — the handler skips those before the
    cache sees them), and fingerprints are sha256 of the canonical plan,
    so identical keys on two brokers really do address the same answer.
    Offline-partial keys carry a distinct prefix so they can never
    collide with whole-result keys."""
    if len(key) == 4 and key[0] == "off":
        _, fingerprint, table, epoch = key
        return f"off|{table}|{epoch}|{fingerprint}"
    fingerprint, table, epoch = key
    return f"res|{table}|{epoch}|{fingerprint}"


class NegativeResultCache:
    """ROADMAP item: memoize EMPTY answers for pruned-to-zero plans.

    Dashboards routinely misfire queries whose partition/time pruning
    selects no segment at all; the answer is empty by construction, yet
    each one still pays routing + scatter + reduce. Entries are sentinel
    bytes keyed by (fingerprint, table, routing epoch) — a segment
    add/replace/remove moves the epoch, so a plan that STOPS pruning to
    zero stops hitting by construction. `skipCache` bypasses it (the
    handler checks cache_bypassed before consulting), and hit/miss
    meters ride the LruTtlCache prefix (`negative_cache_{hits,misses}`).

    Independent of the whole-result cache: it works (and defaults ON)
    even when `pinot.broker.result.cache.enabled` is false, because a
    memoized empty answer can never serve stale DATA — only a stale
    "nothing matches", bounded by epoch + TTL."""

    _SENTINEL = b"0"

    def __init__(self, max_bytes: int = 1 << 20, ttl_seconds: float = 60.0,
                 enabled: bool = True, metrics=None,
                 labels: Optional[dict] = None):
        self.enabled = enabled
        self._cache = LruTtlCache(max_bytes, ttl_seconds, metrics=metrics,
                                  metric_prefix="negative_cache",
                                  labels=labels)

    @classmethod
    def from_config(cls, config, metrics=None,
                    labels: Optional[dict] = None) -> "NegativeResultCache":
        return cls(
            max_bytes=config.get_int("pinot.broker.negative.cache.bytes"),
            ttl_seconds=config.get_float(
                "pinot.broker.negative.cache.ttl.seconds"),
            enabled=config.get_bool("pinot.broker.negative.cache.enabled"),
            metrics=metrics, labels=labels)

    def hit(self, fingerprint: str, table: str, epoch: str) -> bool:
        if not self.enabled:
            return False
        return self._cache.get((fingerprint, table, epoch)) is not None

    def put(self, fingerprint: str, table: str, epoch: str) -> bool:
        if not self.enabled:
            return False
        return self._cache.put((fingerprint, table, epoch), self._SENTINEL)

    def __len__(self) -> int:
        return len(self._cache)

    def drop_table(self, table: str) -> int:
        """Explicitly drop every entry for `table` (matching either the
        logical name or its _OFFLINE/_REALTIME physical forms). Epoch
        keying already makes post-swap entries unaddressable; a segment
        replace (minion merge/purge) calls this anyway so stale
        "nothing matches" memos stop occupying budget immediately
        instead of waiting out TTL + LRU."""
        from pinot_tpu.models import base_table_name
        base = base_table_name(table)
        return self._cache.invalidate(
            lambda k: base_table_name(k[1]) == base)

    @property
    def stats(self):
        return self._cache.stats


class BrokerResultCache:
    """Whole BrokerResponse objects keyed by
    (query fingerprint, table, routing epoch), plus — for hybrid tables —
    the offline side's merged partial keyed by the OFFLINE routing epoch
    (a hybrid query then only re-scatters to the realtime side)."""

    def __init__(self, max_bytes: int = 64 << 20, ttl_seconds: float = 60.0,
                 enabled: bool = True, cache_realtime: bool = False,
                 metrics=None, labels: Optional[dict] = None,
                 backend=None, stale_grace_seconds: float = 0.0):
        """labels: metric labels (e.g. {'broker': id}) — several broker
        handlers in one process share the 'broker' registry, so unlabeled
        gauges would clobber each other.
        backend: a prebuilt cache (cache/tiered.py TieredCache) replacing
        the default local LruTtlCache; remote-capable backends use the
        typed wire codec instead of pickle (a shared store must never
        feed pickle.loads) and fall through on undecodable entries."""
        self.enabled = enabled
        self.cache_realtime = cache_realtime
        if metrics is not None and labels is None:
            labels = {"broker": f"b{next(_broker_ids)}"}
        #: exposed so sibling caches of the SAME broker (negative cache)
        #: can share the instance label instead of minting their own —
        #: dashboards correlate per-broker metrics by this label
        self.labels = labels
        if backend is not None:
            self._cache = backend
            self._wire = getattr(backend, "wire_codec", False)
        else:
            self._cache = LruTtlCache(
                max_bytes, ttl_seconds, metrics=metrics,
                metric_prefix="result_cache", labels=labels,
                stale_grace_seconds=stale_grace_seconds)
            self._wire = False

    @classmethod
    def from_config(cls, config, metrics=None,
                    labels: Optional[dict] = None) -> "BrokerResultCache":
        if metrics is not None and labels is None:
            labels = {"broker": f"b{next(_broker_ids)}"}
        backend = None
        if config.get_str("pinot.broker.result.cache.backend") == "tiered":
            from pinot_tpu.cache.tiered import tiered_backend_from_config
            backend = tiered_backend_from_config(
                config, "pinot.broker.result.cache", "result_cache",
                broker_remote_key, metrics=metrics, labels=labels)
        return cls(
            max_bytes=config.get_int("pinot.broker.result.cache.bytes"),
            ttl_seconds=config.get_float(
                "pinot.broker.result.cache.ttl.seconds"),
            enabled=config.get_bool("pinot.broker.result.cache.enabled"),
            cache_realtime=config.get_bool(
                "pinot.broker.result.cache.realtime"),
            metrics=metrics, labels=labels, backend=backend,
            # retention past TTL costs budget on every expiry — pay it
            # only when brownout (the sole stale reader) can engage
            stale_grace_seconds=(config.get_float(
                "pinot.brownout.stale.ttl.grace.seconds")
                if config.get_bool("pinot.brownout.enabled", True)
                else 0.0))

    # ------------------------------------------------------------------
    def get(self, fingerprint: str, table: str, epoch: str,
            allow_stale: bool = False) -> Optional[BrokerResponse]:
        """allow_stale (brownout rung 2, health/brownout.py): on a
        fresh miss, an expired-but-retained entry within the stale
        grace window may serve, marked ``stale_result=True`` so the
        client sees staleResult=true. Local backend only — a tiered/
        remote backend without get_stale simply never serves stale."""
        if not self.enabled:
            return None
        payload = self._cache.get((fingerprint, table, epoch))
        stale = False
        if payload is None and allow_stale:
            get_stale = getattr(self._cache, "get_stale", None)
            if get_stale is not None:
                payload = get_stale((fingerprint, table, epoch))
                stale = payload is not None
        if payload is None:
            return None
        resp = (wire_loads_response(payload) if self._wire
                else loads(payload))
        if resp is not None and stale:
            resp.stale_result = True
        return resp

    def put(self, fingerprint: str, table: str, epoch: str,
            resp: BrokerResponse) -> bool:
        """Cache only COMPLETE, clean responses — a partial answer (server
        error, missing replica, deadline miss) must re-execute next time,
        not be replayed for a TTL."""
        if not self.enabled or resp.exceptions or resp.trace is not None \
                or resp.partial_result \
                or resp.num_servers_responded != resp.num_servers_queried:
            return False
        payload = (wire_dumps_response(resp) if self._wire else dumps(resp))
        if payload is None:
            return False
        return self._cache.put((fingerprint, table, epoch), payload)

    # -- hybrid-table offline partials ---------------------------------
    def get_offline_partial(self, fingerprint: str, table: str,
                            offline_epoch: str) -> Optional[tuple]:
        """(results, server-level ExecutionStats or None) — the offline
        side's merged per-server results for a hybrid query, keyed by
        the OFFLINE epoch: realtime appends don't move it, so the
        immutable side stays served from cache while the consuming side
        re-executes every time. The stats ride along so a cache-served
        response reports the same pruning counts as an uncached run."""
        if not self.enabled:
            return None
        payload = self._cache.get(("off", fingerprint, table, offline_epoch))
        if payload is None:
            return None
        return (wire_loads_results_stats(payload) if self._wire
                else loads(payload))

    def put_offline_partial(self, fingerprint: str, table: str,
                            offline_epoch: str, results: list,
                            stats=None) -> bool:
        if not self.enabled or not results:
            return False
        payload = (wire_dumps_results(results, extra_stats=stats)
                   if self._wire else dumps((list(results), stats)))
        if payload is None:
            return False
        return self._cache.put(("off", fingerprint, table, offline_epoch),
                               payload)

    def invalidate_table(self, table: str) -> int:
        return self._cache.invalidate(
            lambda k: (k[2] if len(k) == 4 else k[1]) == table)

    def clear(self) -> None:
        self._cache.clear()

    def close(self) -> None:
        """Release a tiered backend's remote connection pool (no-op for
        the local backend)."""
        close = getattr(self._cache, "close", None)
        if close is not None:
            close()

    @property
    def stats(self):
        return self._cache.stats

    @property
    def size_bytes(self) -> int:
        return self._cache.size_bytes

    def __len__(self) -> int:
        return len(self._cache)
