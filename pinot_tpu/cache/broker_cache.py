"""Tier 1: broker whole-result cache.

Reference parity: Druid's `useResultLevelCache` — memoize the FINAL
merged response, keyed by (canonical query fingerprint, logical table,
routing epoch). The epoch is a content hash over the route's segment set
and per-segment versions (broker/routing.py `RoutingTable.epoch`), so a
segment add / replace / remove or time-boundary move changes the key and
stale entries stop being addressable — no explicit invalidation fan-out.

Tables with a realtime side are NOT cached by default: consuming
segments grow without any routing change, and a whole-result hit would
hide freshly ingested rows. `cache_realtime=True` opts in for
append-rare realtime tables that can tolerate TTL-bounded staleness.
"""
from __future__ import annotations

import itertools
from typing import Optional

from pinot_tpu.cache.core import (LruTtlCache, cache_bypassed,  # noqa: F401
                                  dumps, loads)
from pinot_tpu.query.reduce import BrokerResponse

#: default per-instance metric label — several handlers in one process
#: (tests run multiple MiniClusters) share the 'broker' registry
_broker_ids = itertools.count(0)


class BrokerResultCache:
    """Whole BrokerResponse objects keyed by
    (query fingerprint, table, routing epoch)."""

    def __init__(self, max_bytes: int = 64 << 20, ttl_seconds: float = 60.0,
                 enabled: bool = True, cache_realtime: bool = False,
                 metrics=None, labels: Optional[dict] = None):
        """labels: metric labels (e.g. {'broker': id}) — several broker
        handlers in one process share the 'broker' registry, so unlabeled
        gauges would clobber each other."""
        self.enabled = enabled
        self.cache_realtime = cache_realtime
        if metrics is not None and labels is None:
            labels = {"broker": f"b{next(_broker_ids)}"}
        self._cache = LruTtlCache(max_bytes, ttl_seconds, metrics=metrics,
                                  metric_prefix="result_cache",
                                  labels=labels)

    @classmethod
    def from_config(cls, config, metrics=None,
                    labels: Optional[dict] = None) -> "BrokerResultCache":
        return cls(
            max_bytes=config.get_int("pinot.broker.result.cache.bytes"),
            ttl_seconds=config.get_float(
                "pinot.broker.result.cache.ttl.seconds"),
            enabled=config.get_bool("pinot.broker.result.cache.enabled"),
            cache_realtime=config.get_bool(
                "pinot.broker.result.cache.realtime"),
            metrics=metrics, labels=labels)

    # ------------------------------------------------------------------
    def get(self, fingerprint: str, table: str,
            epoch: str) -> Optional[BrokerResponse]:
        if not self.enabled:
            return None
        payload = self._cache.get((fingerprint, table, epoch))
        return loads(payload) if payload is not None else None

    def put(self, fingerprint: str, table: str, epoch: str,
            resp: BrokerResponse) -> bool:
        """Cache only COMPLETE, clean responses — a partial answer (server
        error, missing replica) must re-execute next time, not be replayed
        for a TTL."""
        if not self.enabled or resp.exceptions or resp.trace is not None \
                or resp.num_servers_responded != resp.num_servers_queried:
            return False
        payload = dumps(resp)
        if payload is None:
            return False
        return self._cache.put((fingerprint, table, epoch), payload)

    def invalidate_table(self, table: str) -> int:
        return self._cache.invalidate(lambda k: k[1] == table)

    def clear(self) -> None:
        self._cache.clear()

    @property
    def stats(self):
        return self._cache.stats

    @property
    def size_bytes(self) -> int:
        return self._cache.size_bytes

    def __len__(self) -> int:
        return len(self._cache)
