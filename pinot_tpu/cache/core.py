"""Shared cache mechanics: LRU + TTL + byte budget over opaque payloads.

Both tiers store PICKLED payloads, not live objects: downstream reduce
code mutates result containers in place (IndexedTable-style merges), so
handing out a shared object would let one query's merge corrupt the next
query's cached partial. Serializing on put / deserializing on get makes
every hit a private copy and gives an honest byte count for the budget.
"""
from __future__ import annotations

import pickle
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Hashable, Optional, Tuple


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    puts: int = 0
    evictions: int = 0
    expirations: int = 0
    invalidations: int = 0

    @property
    def hit_ratio(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class LruTtlCache:
    """Thread-safe LRU over byte payloads with a TTL and a byte budget.

    Keys are arbitrary hashables; values are bytes. Eviction order is
    least-recently-USED (get refreshes recency). A payload larger than
    the whole budget is refused rather than evicting everything else.
    """

    def __init__(self, max_bytes: int, ttl_seconds: float,
                 metrics=None, metric_prefix: str = "cache",
                 labels: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_bytes = int(max_bytes)
        self.ttl_seconds = float(ttl_seconds)
        self.stats = CacheStats()
        self._entries: "OrderedDict[Hashable, Tuple[float, bytes]]" = \
            OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._clock = clock
        #: optional MetricsRegistry; hit/miss/eviction meters + byte gauge
        self._metrics = metrics
        self._metric_prefix = metric_prefix
        self._labels = labels

    # ------------------------------------------------------------------
    def _meter(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(f"{self._metric_prefix}_{name}",
                                    labels=self._labels)

    def _gauge_bytes(self) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(f"{self._metric_prefix}_bytes",
                                    self._bytes, labels=self._labels)
            self._metrics.set_gauge(f"{self._metric_prefix}_entries",
                                    len(self._entries), labels=self._labels)

    # ------------------------------------------------------------------
    def get(self, key: Hashable) -> Optional[bytes]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.stats.misses += 1
                self._meter("misses")
                return None
            expires_at, payload = entry
            if self._clock() >= expires_at:
                del self._entries[key]
                self._bytes -= len(payload)
                self.stats.expirations += 1
                self.stats.misses += 1
                self._meter("misses")
                self._gauge_bytes()
                return None
            self._entries.move_to_end(key)
            self.stats.hits += 1
            self._meter("hits")
            return payload

    def put(self, key: Hashable, payload: bytes) -> bool:
        n = len(payload)
        if n > self.max_bytes:
            return False  # would evict the entire cache for one entry
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[1])
            self._entries[key] = (self._clock() + self.ttl_seconds, payload)
            self._bytes += n
            self.stats.puts += 1
            while self._bytes > self.max_bytes:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._bytes -= len(evicted)
                self.stats.evictions += 1
                self._meter("evictions")
            self._gauge_bytes()
        return True

    # ------------------------------------------------------------------
    def invalidate(self, predicate: Callable[[Hashable], bool]) -> int:
        """Drop every entry whose key matches; returns the count."""
        with self._lock:
            doomed = [k for k in self._entries if predicate(k)]
            for k in doomed:
                _, payload = self._entries.pop(k)
                self._bytes -= len(payload)
            self.stats.invalidations += len(doomed)
            self._gauge_bytes()
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self.stats.invalidations += len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._gauge_bytes()

    # ------------------------------------------------------------------
    @property
    def size_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: query options that steer the cache itself (never part of the result)
OPT_SKIP_CACHE = "skipcache"
OPT_USE_CACHE = "usecache"


def cache_bypassed(options: dict) -> bool:
    """True when the query opts out of BOTH tiers via skipCache=true /
    useCache=false."""
    opts = {k.lower(): str(v).lower() for k, v in options.items()}
    return (opts.get(OPT_SKIP_CACHE) == "true"
            or opts.get(OPT_USE_CACHE) == "false")


def dumps(obj: Any) -> Optional[bytes]:
    """Pickle, or None when the object is not serializable (e.g. a result
    carrying a live device buffer) — callers skip caching, never fail the
    query over it."""
    try:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:  # noqa: BLE001 — any serde failure means "don't cache"
        return None


def loads(payload: bytes) -> Any:
    return pickle.loads(payload)
