"""Shared cache mechanics: LRU + TTL + byte budget over opaque payloads.

Both tiers store PICKLED payloads, not live objects: downstream reduce
code mutates result containers in place (IndexedTable-style merges), so
handing out a shared object would let one query's merge corrupt the next
query's cached partial. Serializing on put / deserializing on get makes
every hit a private copy and gives an honest byte count for the budget.
"""
from __future__ import annotations

import pickle
import threading
import time

from pinot_tpu.utils import errorcodes
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Hashable, Optional, Tuple


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    puts: int = 0
    evictions: int = 0
    expirations: int = 0
    invalidations: int = 0

    @property
    def hit_ratio(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class LruTtlCache:
    """Thread-safe LRU over byte payloads with a TTL and a byte budget.

    Keys are arbitrary hashables; values are bytes. Eviction order is
    least-recently-USED (get refreshes recency). A payload larger than
    the whole budget is refused rather than evicting everything else.
    """

    def __init__(self, max_bytes: int, ttl_seconds: float,
                 metrics=None, metric_prefix: str = "cache",
                 labels: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic,
                 stale_grace_seconds: float = 0.0):
        self.max_bytes = int(max_bytes)
        self.ttl_seconds = float(ttl_seconds)
        #: brownout stale-serving window: expired entries are RETAINED
        #: (LRU-evictable, still misses for normal gets) for this long
        #: past TTL so get_stale can serve them flagged; 0 restores
        #: delete-on-expiry exactly
        self.stale_grace_seconds = max(0.0, float(stale_grace_seconds))
        self.stats = CacheStats()
        self._entries: "OrderedDict[Hashable, Tuple[float, bytes]]" = \
            OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._clock = clock
        #: optional MetricsRegistry; hit/miss/eviction meters + byte gauge
        self._metrics = metrics
        self._metric_prefix = metric_prefix
        self._labels = labels

    # ------------------------------------------------------------------
    def _meter(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(f"{self._metric_prefix}_{name}",
                                    labels=self._labels)

    def _gauge_bytes(self) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(f"{self._metric_prefix}_bytes",
                                    self._bytes, labels=self._labels)
            self._metrics.set_gauge(f"{self._metric_prefix}_entries",
                                    len(self._entries), labels=self._labels)

    # ------------------------------------------------------------------
    def get(self, key: Hashable) -> Optional[bytes]:
        hit = self.get_with_ttl(key)
        return None if hit is None else hit[0]

    def get_with_ttl(self, key: Hashable
                     ) -> Optional[Tuple[bytes, float]]:
        """(payload, remaining seconds) or None. The remaining TTL lets
        a tier serving another tier (the cache server) pass freshness
        DOWN: an L1 back-fill stamped with a fresh full TTL would extend
        the operator's staleness budget by up to 2x."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.stats.misses += 1
                self._meter("misses")
                return None
            expires_at, payload = entry
            now = self._clock()
            if now >= expires_at:
                if now >= expires_at + self.stale_grace_seconds:
                    del self._entries[key]
                    self._bytes -= len(payload)
                    self.stats.expirations += 1
                self.stats.misses += 1
                self._meter("misses")
                self._gauge_bytes()
                return None
            self._entries.move_to_end(key)
            self.stats.hits += 1
            self._meter("hits")
            return payload, expires_at - now

    def get_stale(self, key: Hashable) -> Optional[bytes]:
        """An entry within TTL *or* the stale grace window — the
        brownout rung-2 read path (health/brownout.py): past TTL the
        payload is knowingly stale, the caller flags it staleResult.
        None when absent or past TTL + grace (which also reclaims)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            expires_at, payload = entry
            now = self._clock()
            if now >= expires_at + self.stale_grace_seconds:
                del self._entries[key]
                self._bytes -= len(payload)
                self.stats.expirations += 1
                self._gauge_bytes()
                return None
            self._entries.move_to_end(key)
            if now >= expires_at:
                self._meter("stale_hits")
            return payload

    def put(self, key: Hashable, payload: bytes,
            ttl_seconds: Optional[float] = None) -> bool:
        """ttl_seconds overrides the cache default for THIS entry — the
        remote cache server stores entries from tiers with different
        freshness budgets, so TTL travels with the payload."""
        n = len(payload)
        if n > self.max_bytes:
            return False  # would evict the entire cache for one entry
        ttl = self.ttl_seconds if ttl_seconds is None else float(ttl_seconds)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[1])
            self._entries[key] = (self._clock() + ttl, payload)
            self._bytes += n
            self.stats.puts += 1
            while self._bytes > self.max_bytes:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._bytes -= len(evicted)
                self.stats.evictions += 1
                self._meter("evictions")
            self._gauge_bytes()
        return True

    # ------------------------------------------------------------------
    def invalidate(self, predicate: Callable[[Hashable], bool]) -> int:
        """Drop every entry whose key matches; returns the count."""
        with self._lock:
            doomed = [k for k in self._entries if predicate(k)]
            for k in doomed:
                _, payload = self._entries.pop(k)
                self._bytes -= len(payload)
            self.stats.invalidations += len(doomed)
            self._gauge_bytes()
            return len(doomed)

    def remove(self, key: Hashable) -> bool:
        """O(1) keyed drop (invalidate() is a full scan — the cache
        server's single-key DELETE must not stall every replica's
        GET/SET behind an O(#entries) walk under the lock)."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return False
            self._bytes -= len(entry[1])
            self.stats.invalidations += 1
            self._gauge_bytes()
            return True

    def clear(self) -> None:
        with self._lock:
            self.stats.invalidations += len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._gauge_bytes()

    # ------------------------------------------------------------------
    @property
    def size_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: query options that steer the cache itself (never part of the result)
OPT_SKIP_CACHE = "skipcache"
OPT_USE_CACHE = "usecache"


def cache_bypassed(options: dict) -> bool:
    """True when the query opts out of BOTH tiers via skipCache=true /
    useCache=false."""
    opts = {k.lower(): str(v).lower() for k, v in options.items()}
    return (opts.get(OPT_SKIP_CACHE) == "true"
            or opts.get(OPT_USE_CACHE) == "false")


def dumps(obj: Any) -> Optional[bytes]:
    """Pickle, or None when the object is not serializable (e.g. a result
    carrying a live device buffer) — callers skip caching, never fail the
    query over it."""
    try:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:  # noqa: BLE001 — any serde failure means "don't cache"
        return None


def loads(payload: bytes) -> Any:
    return pickle.loads(payload)


# ---------------------------------------------------------------------------
# Wire codec: payloads that may cross the process boundary to the remote
# cache tier. Pickle is fine for in-process copies but must never be
# deserialized from a SHARED store (a poisoned entry would execute code on
# every replica), so remote-capable tiers use the same typed DataTable
# encoding the server->broker plane already speaks (server/datatable.py).
# Decoding NEVER raises: an undecodable/foreign entry degrades to a miss.
# ---------------------------------------------------------------------------

#: payload discriminator tags (first byte of a wire payload)
_WIRE_RESULTS = b"R"   # list of shape-tagged segment/partial results
_WIRE_RESPONSE = b"B"  # one whole BrokerResponse


def wire_dumps_results(results: list, extra_stats=None) -> Optional[bytes]:
    """Encode a list of segment-result objects (+ optional server-level
    ExecutionStats riding alongside, e.g. pruning counts for a cached
    offline partial); None when any element is outside the typed
    registry (callers skip caching, never fail)."""
    from pinot_tpu.server import datatable
    try:
        return _WIRE_RESULTS + datatable.serialize_results(
            list(results), extra_stats=extra_stats)
    except Exception:  # noqa: BLE001 — "don't cache", never "fail query"
        return None


def wire_loads_results(payload: bytes) -> Optional[list]:
    out = wire_loads_results_stats(payload)
    return None if out is None else out[0]


def wire_loads_results_stats(payload: bytes) -> Optional[tuple]:
    """(results, extra ExecutionStats or None), or None on any decode
    failure — undecodable entry == miss."""
    from pinot_tpu.server import datatable
    try:
        if not payload or payload[:1] != _WIRE_RESULTS:
            return None
        results, exceptions, stats = \
            datatable.deserialize_results(payload[1:])
        if exceptions:
            return None
        return results, stats
    except Exception:  # noqa: BLE001 — undecodable entry == miss
        return None


def wire_dumps_response(resp: Any) -> Optional[bytes]:
    """Encode a BrokerResponse (trace-less, complete — the broker cache
    refuses anything else before calling this)."""
    from pinot_tpu.server import datatable
    try:
        rt = resp.result_table
        table = (None if rt is None
                 else (list(rt.columns), list(rt.column_types),
                       [tuple(r) for r in rt.rows]))
        blob = (
            table,
            [(int(e.get("errorCode", errorcodes.QUERY_EXECUTION)),
              str(e.get("message", "")))
             for e in resp.exceptions],
            datatable._stats_tuple(resp.stats),
            int(resp.num_servers_queried),
            int(resp.num_servers_responded),
            bool(resp.num_groups_limit_reached),
        )
        return _WIRE_RESPONSE + datatable.serialize_value(blob)
    except Exception:  # noqa: BLE001
        return None


def wire_loads_response(payload: bytes) -> Optional[Any]:
    from pinot_tpu.query.reduce import BrokerResponse, ResultTable
    from pinot_tpu.server import datatable
    try:
        if not payload or payload[:1] != _WIRE_RESPONSE:
            return None
        table, exc, stats, queried, responded, groups_limit = \
            datatable.deserialize_value(payload[1:])
        resp = BrokerResponse()
        if table is not None:
            cols, types, rows = table
            resp.result_table = ResultTable(list(cols), list(types),
                                            [tuple(r) for r in rows])
        resp.exceptions = [{"errorCode": c, "message": m} for c, m in exc]
        resp.stats = datatable._stats_from(stats)
        resp.num_servers_queried = queried
        resp.num_servers_responded = responded
        resp.num_groups_limit_reached = groups_limit
        return resp
    except Exception:  # noqa: BLE001 — undecodable entry == miss
        return None


