"""Remote cache tier: standalone cache-server role + resilient client.

Reference parity: the memcached-style shared tier Druid deploys behind
`useCache`/`populateCache` (druid.cache.type=memcached) and Pinot's
shared-response-store proposals. One cache-server process holds a single
`LruTtlCache` byte budget; every broker/server replica mounts it as L2
through `RemoteCacheBackend`, so a result computed once warms the whole
fleet.

Wire protocol (utils/netframe.py framing, u32 LE length-prefixed):

  request : JSON {"op": get|set|delete|stats|clear|ping, "key": str,
                  "ttl": float?}  [+ one RAW payload frame when op=set]
  response: JSON {"ok": bool, "hit": bool?, "stats": {...}?, "error": str?}
            [+ one RAW payload frame when op=get hit]

Keys are STRINGS: callers map their tuple keys to stable strings (and
return None for keys that must not be shared — e.g. segment versions
that are process-local generation stamps, not content CRCs).

Failure semantics: the client NEVER raises into a query. Every error
path returns miss/False, feeds the circuit breaker (CLOSED -> OPEN after
K consecutive failures, OPEN -> HALF_OPEN probe after a cooldown,
HALF_OPEN -> CLOSED on one success), and is metered. An unreachable
cache server therefore degrades the fabric to L1-only at the cost of one
fast refused connection per probe window.
"""
from __future__ import annotations

import logging
import queue
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional

from pinot_tpu.cache.core import LruTtlCache
from pinot_tpu.segment import codec
from pinot_tpu.utils import tracing
from pinot_tpu.utils.failpoints import FailpointError, fire
from pinot_tpu.utils.netframe import (MAX_FRAME, recv_frame, recv_raw_frame,
                                      send_frame, send_raw_frame)

log = logging.getLogger(__name__)

#: compressed-payload wrapper: magic + u8 codec id + u32 raw size, then
#: the codec output. Distinct from the DataTable wire magic ('PDT1'), so
#: raw entries can never be mistaken for wrapped ones.
_COMPRESS_MAGIC = b"PZC1"
_COMPRESS_HDR = struct.Struct("<BI")


def _wrap_payload(payload: bytes, threshold: int) -> bytes:
    """Compress payloads at/above the threshold with the segment codecs
    (ZSTANDARD when the wheel is present, GZIP otherwise — codec.resolve
    picks, and the wrapper records the codec actually used so readers
    never guess). Incompressible payloads ship raw: the wrapper is only
    kept when it actually shrinks the wire bytes."""
    if threshold <= 0 or len(payload) < threshold:
        return payload
    cid, comp = codec.compress(payload, codec.ZSTANDARD)
    wrapped = _COMPRESS_MAGIC + _COMPRESS_HDR.pack(cid, len(payload)) + comp
    return wrapped if len(wrapped) < len(payload) else payload


def _unwrap_payload(payload: bytes) -> Optional[bytes]:
    """Transparent decode of a wrapped payload; raw payloads pass
    through. None on a torn/corrupt wrapper — callers degrade to miss
    (the shared-tier contract: never raise into a query)."""
    if not payload.startswith(_COMPRESS_MAGIC):
        return payload
    try:
        cid, raw_size = _COMPRESS_HDR.unpack_from(payload,
                                                  len(_COMPRESS_MAGIC))
        out = codec.decompress(
            payload[len(_COMPRESS_MAGIC) + _COMPRESS_HDR.size:],
            cid, raw_size)
        if len(out) != raw_size:
            return None
        return out
    except Exception:  # noqa: BLE001 — torn/corrupt entry = miss
        return None


class CacheServer:
    """The cache-server role: GET/SET/DELETE/STATS over TCP, per-entry TTL.

    One thread per connection (socketserver.ThreadingTCPServer, same shape
    as controller/coordination.py); the LruTtlCache lock makes each op
    atomic, so concurrent SET/GET on one key always observe a whole
    payload, never a torn one."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_bytes: int = 512 << 20, ttl_seconds: float = 300.0,
                 metrics=None):
        self.cache = LruTtlCache(max_bytes, ttl_seconds, metrics=metrics,
                                 metric_prefix="cache_server")
        server = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                with server._conn_lock:
                    server._conns.add(sock)
                try:
                    while True:
                        req = recv_frame(sock)
                        if req is None:
                            return
                        server._serve_one(sock, req)
                except (ConnectionError, OSError, ValueError):
                    pass  # client vanished / oversized frame: drop conn
                finally:
                    with server._conn_lock:
                        server._conns.discard(sock)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._server = _Server((host, port), _Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def _serve_one(self, sock: socket.socket, req: dict) -> None:
        op = req.get("op")
        key = req.get("key")
        if op == "set":
            # payload frame ALWAYS follows a set header — read it even
            # when the entry will be refused, or the stream desyncs
            payload = recv_raw_frame(sock)
            if payload is None:
                raise ConnectionError("set without payload")
            ok = isinstance(key, str) and self.cache.put(
                key, payload, ttl_seconds=req.get("ttl"))
            send_frame(sock, {"ok": bool(ok)})
        elif op == "get":
            hit = (self.cache.get_with_ttl(key)
                   if isinstance(key, str) else None)
            if hit is None:
                send_frame(sock, {"ok": True, "hit": False})
            else:
                payload, remaining = hit
                # remaining TTL rides along so the client's L1 back-fill
                # inherits the entry's freshness instead of restarting it
                send_frame(sock, {"ok": True, "hit": True,
                                  "ttl": round(remaining, 3)})
                send_raw_frame(sock, payload)
        elif op == "delete":
            n = int(self.cache.remove(key))
            send_frame(sock, {"ok": True, "deleted": n})
        elif op == "stats":
            st = self.cache.stats
            send_frame(sock, {"ok": True, "stats": {
                "hits": st.hits, "misses": st.misses, "puts": st.puts,
                "evictions": st.evictions, "expirations": st.expirations,
                "entries": len(self.cache),
                "bytes": self.cache.size_bytes}})
        elif op == "clear":
            self.cache.clear()
            send_frame(sock, {"ok": True})
        elif op == "ping":
            send_frame(sock, {"ok": True})
        else:
            send_frame(sock, {"ok": False, "error": f"bad op {op!r}"})

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"cache-server-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        """Full outage semantics, matching a process kill: the listener
        closes AND every established connection is severed, so in-process
        fault-injection tests exercise the same client error paths a real
        crash would."""
        self._server.shutdown()
        self._server.server_close()
        with self._conn_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

#: circuit states (exported as the breaker gauge value)
CIRCUIT_CLOSED, CIRCUIT_HALF_OPEN, CIRCUIT_OPEN = 0, 1, 2


class CircuitBreaker:
    """Trip after `failure_threshold` CONSECUTIVE failures; after
    `reset_seconds` let exactly ONE probe through (half-open); a probe
    success closes the circuit, a probe failure re-opens the window."""

    def __init__(self, failure_threshold: int = 3,
                 reset_seconds: float = 5.0,
                 clock=time.monotonic, on_state_change=None):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_seconds = float(reset_seconds)
        self._clock = clock
        self._state = CIRCUIT_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._lock = threading.Lock()
        self._on_state_change = on_state_change

    def _set_state(self, state: int) -> None:
        if state != self._state:
            self._state = state
            if self._on_state_change is not None:
                self._on_state_change(state)

    @property
    def state(self) -> int:
        with self._lock:
            if self._state == CIRCUIT_OPEN and \
                    self._clock() - self._opened_at >= self.reset_seconds:
                return CIRCUIT_HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """May a request go out now? In half-open, only the first caller
        gets through until its verdict lands."""
        with self._lock:
            if self._state == CIRCUIT_CLOSED:
                return True
            if self._clock() - self._opened_at >= self.reset_seconds:
                self._set_state(CIRCUIT_HALF_OPEN)
                if not self._probe_in_flight:
                    self._probe_in_flight = True
                    return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_in_flight = False
            self._set_state(CIRCUIT_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_in_flight = False
            if self._state != CIRCUIT_CLOSED:
                # failed probe: restart the cooldown window
                self._opened_at = self._clock()
                self._set_state(CIRCUIT_OPEN)
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._set_state(CIRCUIT_OPEN)


class _CacheConnection:
    """One pooled socket to the cache server. NOT thread-safe by itself —
    the pool hands a connection to one request at a time."""

    def __init__(self, host: str, port: int, timeout: float):
        self.host, self.port, self.timeout = host, port, timeout
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout)
        return self._sock

    def request(self, header: dict,
                payload: Optional[bytes] = None) -> tuple:
        """Returns (response header dict, response payload or None)."""
        sock = self._connect()
        send_frame(sock, header)
        if payload is not None:
            send_raw_frame(sock, payload)
        resp = recv_frame(sock)
        if resp is None:
            raise ConnectionError("cache server closed connection")
        body = None
        if resp.get("hit"):
            body = recv_raw_frame(sock)
            if body is None:
                raise ConnectionError("cache server closed mid-payload")
        return resp, body

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class RemoteCacheBackend:
    """Client for one cache server: connection pool + timeouts + breaker.

    All operations are total functions — get returns None, put/delete
    return False on ANY failure (timeout, refused, breaker open, frame
    too large), never an exception. Metrics: remote_cache_{hits,misses,
    errors,rejected} meters, remote_cache_request timer, and a
    remote_cache_breaker_state gauge (0=closed 1=half-open 2=open)."""

    def __init__(self, address: str, timeout_seconds: float = 2.0,
                 pool_size: int = 2, failure_threshold: int = 3,
                 reset_seconds: float = 5.0, metrics=None,
                 labels: Optional[dict] = None,
                 compress_threshold: int = 0):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.timeout = float(timeout_seconds)
        #: payloads at/above this size are codec-wrapped before the wire
        #: (pinot.cache.server.compress.threshold.bytes; <= 0 disables).
        #: Compression is CLIENT-side: the cache server stores opaque
        #: bytes, so one compressing client warms the whole fleet and
        #: every mount must share the wrapper format (it does — the
        #: magic + codec id ride in the payload itself)
        self.compress_threshold = int(compress_threshold)
        self._metrics = metrics
        self._labels = labels
        self.breaker = CircuitBreaker(failure_threshold, reset_seconds,
                                      on_state_change=self._gauge_state)
        self._pool: "queue.Queue[_CacheConnection]" = queue.Queue()
        for _ in range(max(1, int(pool_size))):
            self._pool.put(_CacheConnection(host, self.port, self.timeout))
        self._gauge_state(CIRCUIT_CLOSED)
        #: local tallies mirroring the meters (cheap asserts in tests)
        self.hits = 0
        self.misses = 0
        self.errors = 0

    # -- metrics -------------------------------------------------------
    def _meter(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(f"remote_cache_{name}",
                                    labels=self._labels)

    def _gauge_state(self, state: int) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("remote_cache_breaker_state", state,
                                    labels=self._labels)

    # -- core request plumbing ----------------------------------------
    def _request(self, header: dict,
                 payload: Optional[bytes] = None) -> Optional[tuple]:
        """One breaker-guarded round trip; None when rejected/failed."""
        if not self.breaker.allow():
            self._meter("rejected")
            return None
        try:
            conn = self._pool.get(timeout=self.timeout)
        except queue.Empty:
            # every pooled channel busy past the deadline: treat as a
            # availability failure, not a correctness one
            self.errors += 1
            self._meter("errors")
            self.breaker.record_failure()
            return None
        try:
            t0 = time.perf_counter()
            out = conn.request(header, payload)
            if self._metrics is not None:
                self._metrics.add_timing(
                    "remote_cache_request",
                    (time.perf_counter() - t0) * 1000.0, labels=self._labels)
            self.breaker.record_success()
            return out
        except (ConnectionError, OSError, ValueError) as e:
            conn.close()
            self.errors += 1
            self._meter("errors")
            self.breaker.record_failure()
            log.debug("remote cache request failed: %s", e)
            return None
        finally:
            self._pool.put(conn)

    # -- public ops ----------------------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        hit = self.get_with_ttl(key)
        return None if hit is None else hit[0]

    def get_with_ttl(self, key: str
                     ) -> Optional[tuple]:
        """(payload, remaining server-side TTL seconds or None)."""
        if not tracing.active():
            return self._get_with_ttl(key)
        # traced hop: the span times the RTT client-side, and the trace
        # id rides the request header so cache-server logs/stats can
        # correlate an op back to the query that issued it
        with tracing.Scope("RemoteCacheGet",
                           node=f"{self.host}:{self.port}") as sc:
            out = self._get_with_ttl(key, tracing.current_trace_id())
            sc.set(hit=out is not None,
                   bytes=len(out[0]) if out is not None else 0)
            return out

    def _get_with_ttl(self, key: str,
                      trace_id: Optional[str] = None) -> Optional[tuple]:
        try:
            # chaos site: a slow/dead/lying remote tier — the breaker and
            # the total-function contract below must absorb all of it
            fire("cache.remote.get", key=key)
        except (ConnectionError, FailpointError):
            self.errors += 1
            self._meter("errors")
            self.breaker.record_failure()
            return None
        header: Dict[str, object] = {"op": "get", "key": key}
        if trace_id:
            header["trace"] = trace_id
        out = self._request(header)
        if out is None:
            return None
        resp, body = out
        if resp.get("hit") and body is not None:
            body = _unwrap_payload(body)
            if body is None:
                # torn/corrupt compressed entry: degrade to miss (the
                # caller recomputes; the entry ages out or is rewritten)
                self.misses += 1
                self._meter("misses")
                return None
            self.hits += 1
            self._meter("hits")
            ttl = resp.get("ttl")
            return body, (float(ttl) if ttl is not None else None)
        self.misses += 1
        self._meter("misses")
        return None

    def put(self, key: str, payload: bytes,
            ttl_seconds: Optional[float] = None) -> bool:
        wrapped = _wrap_payload(payload, self.compress_threshold)
        if wrapped is not payload:
            if self._metrics is not None:
                self._metrics.add_meter("remote_cache_compressed_bytes",
                                        len(wrapped), labels=self._labels)
            payload = wrapped
        if len(payload) > MAX_FRAME:
            return False
        header: Dict[str, object] = {"op": "set", "key": key}
        if ttl_seconds is not None:
            header["ttl"] = float(ttl_seconds)
        if not tracing.active():
            out = self._request(header, payload)
            return bool(out is not None and out[0].get("ok"))
        tid = tracing.current_trace_id()
        if tid:
            header["trace"] = tid
        with tracing.Scope("RemoteCachePut",
                           node=f"{self.host}:{self.port}",
                           bytes=len(payload)):
            out = self._request(header, payload)
            return bool(out is not None and out[0].get("ok"))

    def delete(self, key: str) -> bool:
        out = self._request({"op": "delete", "key": key})
        return bool(out is not None and out[0].get("ok"))

    def stats(self) -> Optional[dict]:
        out = self._request({"op": "stats"})
        return out[0].get("stats") if out is not None else None

    def clear(self) -> bool:
        out = self._request({"op": "clear"})
        return bool(out is not None and out[0].get("ok"))

    def ping(self) -> bool:
        out = self._request({"op": "ping"})
        return bool(out is not None and out[0].get("ok"))

    def close(self) -> None:
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                return
