"""Consistent-hash cache ring: the remote L2 tier over N cache servers.

A single cache-server role is a cache-fabric SPOF: one process death
cold-starts every replica's L2 at once, and one box bounds the shared
tier's capacity. This module shards the remote key space CLIENT-side
over N cache-server addresses with a consistent-hash ring:

  * virtual nodes — each address hashes to `vnodes` points on the ring,
    so key ranges spread evenly and removing one node redistributes only
    ~1/N of the space (no rehash storm: the other nodes' key ranges are
    untouched, their warm entries stay addressable).
  * per-node circuit breakers — each address is a full
    `RemoteCacheBackend` (pool, timeouts, breaker, metrics labeled with
    `cache_node`). A dead node's key range degrades to L1-only (gets
    miss, puts drop) while every other range keeps serving; keys are
    deliberately NOT re-mapped to surviving nodes on failure — a brief
    network blip would otherwise bounce a range between nodes and serve
    stale entries after writes landed elsewhere.
  * membership from config + health — the address list comes from the
    `...remote.address` knob (comma-separated); `add_node`/`remove_node`
    support operational resize, and health is the breaker's business.

The `cache.ring.node` failpoint fires on every key->node resolution with
the chosen node, so chaos schedules can kill exactly one node's range
(`where={"node": addr}`) deterministically.
"""
from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, List, Optional, Sequence

from pinot_tpu.cache.remote import RemoteCacheBackend
from pinot_tpu.utils.failpoints import FailpointError, fire


def _point(s: str) -> int:
    """Stable 64-bit ring position (process-independent, unlike hash())."""
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8], "big")


class ConsistentHashRing:
    """Key -> node mapping with virtual nodes. Thread-safe; mutation
    (add/remove) rebuilds the sorted point list atomically."""

    def __init__(self, nodes: Sequence[str] = (), vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._nodes: List[str] = []
        self._points: List[int] = []
        self._owners: List[str] = []
        self._lock = threading.Lock()
        for n in nodes:
            self.add_node(n)

    def _rebuild_locked(self) -> None:
        pts = []
        for node in self._nodes:
            for i in range(self.vnodes):
                pts.append((_point(f"{node}#{i}"), node))
        pts.sort()
        self._points = [p for p, _n in pts]
        self._owners = [n for _p, n in pts]

    def add_node(self, node: str) -> None:
        with self._lock:
            if node not in self._nodes:
                self._nodes.append(node)
                self._rebuild_locked()

    def remove_node(self, node: str) -> None:
        with self._lock:
            if node in self._nodes:
                self._nodes.remove(node)
                self._rebuild_locked()

    @property
    def nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def node_for(self, key: str) -> Optional[str]:
        """The node owning `key`'s range (clockwise successor point);
        None on an empty ring."""
        with self._lock:
            if not self._points:
                return None
            idx = bisect.bisect(self._points, _point(key))
            return self._owners[idx % len(self._owners)]


class RingRemoteCacheBackend:
    """Drop-in for `RemoteCacheBackend` (same total-function surface:
    get/get_with_ttl/put/delete/stats/clear/ping/close never raise into
    a query) that routes each key to its ring node. `TieredCache` mounts
    it unchanged, so the `...remote.address` knob growing a comma is the
    whole migration."""

    def __init__(self, addresses: Sequence[str], vnodes: int = 64,
                 timeout_seconds: float = 2.0, pool_size: int = 2,
                 failure_threshold: int = 3, reset_seconds: float = 5.0,
                 metrics=None, labels: Optional[dict] = None,
                 compress_threshold: int = 0):
        addresses = [a.strip() for a in addresses if a and a.strip()]
        if not addresses:
            raise ValueError("cache ring needs at least one address")
        self.ring = ConsistentHashRing(addresses, vnodes=vnodes)
        self.backends: Dict[str, RemoteCacheBackend] = {}
        self._metrics = metrics
        self._labels = labels
        self._backend_kwargs = dict(
            timeout_seconds=timeout_seconds, pool_size=pool_size,
            failure_threshold=failure_threshold,
            reset_seconds=reset_seconds,
            compress_threshold=compress_threshold)
        for addr in addresses:
            self._add_backend(addr)

    def _add_backend(self, addr: str) -> None:
        labels = dict(self._labels or {})
        labels["cache_node"] = addr
        self.backends[addr] = RemoteCacheBackend(
            addr, metrics=self._metrics, labels=labels,
            **self._backend_kwargs)

    # -- membership ----------------------------------------------------
    def add_node(self, addr: str) -> None:
        """Operational resize: only ~1/N of the key space re-maps (those
        ranges cold-start; everything else stays warm)."""
        if addr not in self.backends:
            self._add_backend(addr)
        self.ring.add_node(addr)

    def remove_node(self, addr: str) -> None:
        self.ring.remove_node(addr)
        b = self.backends.pop(addr, None)
        if b is not None:
            b.close()

    # -- key routing ---------------------------------------------------
    def _backend_for(self, key: str) -> Optional[RemoteCacheBackend]:
        addr = self.ring.node_for(key)
        if addr is None:
            return None
        backend = self.backends.get(addr)
        if backend is None:
            return None
        try:
            # chaos site: one node's key range misbehaving — the per-node
            # breaker and the miss-degradation below absorb all of it
            fire("cache.ring.node", node=addr, key=key)
        except (ConnectionError, FailpointError):
            backend.errors += 1
            backend.breaker.record_failure()
            if self._metrics is not None:
                self._metrics.add_meter("remote_cache_errors",
                                        labels={**(self._labels or {}),
                                                "cache_node": addr})
            return None
        return backend

    # -- RemoteCacheBackend surface ------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        hit = self.get_with_ttl(key)
        return None if hit is None else hit[0]

    def get_with_ttl(self, key: str) -> Optional[tuple]:
        backend = self._backend_for(key)
        if backend is None:
            return None
        return backend.get_with_ttl(key)

    def put(self, key: str, payload: bytes,
            ttl_seconds: Optional[float] = None) -> bool:
        backend = self._backend_for(key)
        if backend is None:
            return False
        return backend.put(key, payload, ttl_seconds=ttl_seconds)

    def delete(self, key: str) -> bool:
        backend = self._backend_for(key)
        if backend is None:
            return False
        return backend.delete(key)

    def stats(self) -> Optional[dict]:
        """Per-node server stats keyed by address (None for unreachable
        nodes) — the fleet view, not a single box's."""
        return {addr: b.stats() for addr, b in self.backends.items()}

    def clear(self) -> bool:
        ok = True
        for b in self.backends.values():
            ok = b.clear() and ok
        return ok

    def ping(self) -> bool:
        """True when EVERY member answers (fleet health; per-node health
        is the breakers' gauge)."""
        ok = True
        for b in self.backends.values():
            ok = b.ping() and ok
        return ok

    def close(self) -> None:
        for b in self.backends.values():
            b.close()

    # -- aggregated client tallies (test/bench parity) ------------------
    @property
    def hits(self) -> int:
        return sum(b.hits for b in self.backends.values())

    @property
    def misses(self) -> int:
        return sum(b.misses for b in self.backends.values())

    @property
    def errors(self) -> int:
        return sum(b.errors for b in self.backends.values())

    def breaker_of(self, addr: str):
        b = self.backends.get(addr)
        return None if b is None else b.breaker
