"""Tier 2: server-side per-segment partial-result cache.

Reference parity: Druid's historical segment cache (`useCache` /
`populateCache`, immutable segments only) mapped onto this repo's
ImmutableSegment / consuming-segment split. Cached unit: ONE segment's
aggregation / group-by / distinct partial for ONE plan fingerprint.
Consuming (mutable) segments and upsert segments (live `valid_doc_ids`)
are never cached — the mutable tail always re-executes, which is exactly
what keeps hybrid tables fresh while the immutable bulk is served from
cache.

Invalidation is version-based: the key carries `segment_version()` —
content CRC when the segment has one, else a per-process generation
stamp — so a replace-by-name simply addresses a different key and the
old entry ages out. `TableDataManager` additionally calls
`invalidate_segment` on replace/remove for prompt byte reclamation.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Optional

from pinot_tpu.cache.core import (LruTtlCache, dumps, loads,
                                  wire_dumps_results, wire_loads_results)
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.loader import ImmutableSegment

#: per-process generation stamps for segments without a content CRC —
#: monotonically increasing, never reused, so two same-named segment
#: objects (a replace) can never collide on a key
_gen_counter = itertools.count(1)
_gen_lock = threading.Lock()


def segment_version(segment: Any):
    """Stable version token for a loaded segment: the content CRC when
    present (survives reload of the same directory), else a per-object
    generation stamp (unique per process)."""
    crc = getattr(getattr(segment, "metadata", None), "crc", 0)
    if crc:
        return ("crc", crc)
    gen = getattr(segment, "_ptpu_cache_gen", None)
    if gen is None:
        with _gen_lock:
            gen = getattr(segment, "_ptpu_cache_gen", None)
            if gen is None:
                gen = next(_gen_counter)
                try:
                    segment._ptpu_cache_gen = gen
                except AttributeError:
                    return ("id", id(segment))  # slotted object: best effort
    return ("gen", gen)


def is_cacheable_segment(segment: Any) -> bool:
    """Immutable AND no live validity bitmap (upsert mutates
    `valid_doc_ids` in place without a version change)."""
    return (isinstance(segment, ImmutableSegment)
            and getattr(segment, "valid_doc_ids", None) is None)


def is_cacheable_shape(ctx: QueryContext) -> bool:
    """Aggregation / group-by / distinct partials only: selection results
    are large, cheap to recompute, and LIMIT-dependent per segment."""
    return bool(ctx.aggregations) or ctx.distinct


def segment_remote_key(key) -> Optional[str]:
    """Tuple key -> wire key string for the shared remote tier, or None
    when the entry must stay process-local: 'gen'/'id' version stamps are
    per-process counters — identical stamps on two instances would alias
    DIFFERENT segment contents, so only content-CRC versions are shared."""
    name, version, plan_fp = key
    if not (isinstance(version, tuple) and version[0] == "crc"):
        return None
    return f"seg|{name}|crc:{version[1]}|{plan_fp}"


class SegmentResultCache:
    """Per-segment partial results keyed by
    (segment name, segment version, plan fingerprint)."""

    def __init__(self, max_bytes: int = 256 << 20,
                 ttl_seconds: float = 300.0, enabled: bool = True,
                 metrics=None, labels: Optional[dict] = None,
                 backend=None):
        """labels: metric labels (e.g. {'instance': id}) — several server
        instances in one process share the 'server' registry, so unlabeled
        gauges would clobber each other.
        backend: a prebuilt cache (e.g. cache/tiered.py TieredCache) to
        use instead of the default local LruTtlCache. Remote-capable
        backends switch the payload codec from pickle to the typed wire
        encoding (cache/core.py wire_*): a shared store must never feed
        pickle.loads, and an undecodable entry degrades to a miss."""
        self.enabled = enabled
        if backend is not None:
            self._cache = backend
            self._wire = getattr(backend, "wire_codec", False)
        else:
            self._cache = LruTtlCache(max_bytes, ttl_seconds,
                                      metrics=metrics,
                                      metric_prefix="segment_result_cache",
                                      labels=labels)
            self._wire = False

    @classmethod
    def from_config(cls, config, metrics=None,
                    labels: Optional[dict] = None) -> "SegmentResultCache":
        backend = None
        if config.get_str("pinot.server.segment.cache.backend") == "tiered":
            from pinot_tpu.cache.tiered import tiered_backend_from_config
            backend = tiered_backend_from_config(
                config, "pinot.server.segment.cache",
                "segment_result_cache", segment_remote_key,
                metrics=metrics, labels=labels)
        return cls(
            max_bytes=config.get_int("pinot.server.segment.cache.bytes"),
            ttl_seconds=config.get_float(
                "pinot.server.segment.cache.ttl.seconds"),
            enabled=config.get_bool("pinot.server.segment.cache.enabled"),
            metrics=metrics, labels=labels, backend=backend)

    # ------------------------------------------------------------------
    def _decode(self, payload: bytes) -> Optional[Any]:
        if self._wire:
            results = wire_loads_results(payload)
            return results[0] if results else None
        return loads(payload)

    def _encode(self, result: Any) -> Optional[bytes]:
        return wire_dumps_results([result]) if self._wire else dumps(result)

    def get(self, segment: Any, plan_fp: str) -> Optional[Any]:
        if not self.enabled or not is_cacheable_segment(segment):
            return None
        payload = self._cache.get(
            (segment.name, segment_version(segment), plan_fp))
        if payload is None:
            return None
        # workload accounting: serving this partial cost the cache tier
        # these bytes instead of a re-execution (per-query attribution)
        from pinot_tpu.utils.accounting import current_slip
        slip = current_slip()
        if slip is not None:
            slip.add(cache_hit_bytes=len(payload))
        return self._decode(payload)

    def put(self, segment: Any, plan_fp: str, result: Any) -> bool:
        if not self.enabled or not is_cacheable_segment(segment):
            return False
        payload = self._encode(result)
        if payload is None:
            return False
        # a put is the byte-priced face of a MISS: these bytes had to be
        # computed (and written) because no tier held them
        from pinot_tpu.utils.accounting import current_slip
        slip = current_slip()
        if slip is not None:
            slip.add(cache_miss_bytes=len(payload))
        return self._cache.put(
            (segment.name, segment_version(segment), plan_fp), payload)

    def invalidate_segment(self, name: str, except_version=None) -> int:
        """Drop cached partials for the named segment. except_version
        spares entries of ONE version — a refresh-push replaces the
        segment right after warmup populated the NEW version's entries,
        and a name-only purge would wipe that warmup work along with the
        stale version."""
        return self._cache.invalidate(
            lambda k: k[0] == name and (except_version is None
                                        or k[1] != except_version))

    def clear(self) -> None:
        self._cache.clear()

    def close(self) -> None:
        """Release a tiered backend's remote connection pool (no-op for
        the local backend)."""
        close = getattr(self._cache, "close", None)
        if close is not None:
            close()

    @property
    def stats(self):
        return self._cache.stats

    @property
    def size_bytes(self) -> int:
        return self._cache.size_bytes

    def __len__(self) -> int:
        return len(self._cache)
