"""TieredCache: process-local L1 over a shared remote L2.

Composes the PR-1 `LruTtlCache` (L1) with a `RemoteCacheBackend` (L2)
behind the SAME byte-payload interface, so `BrokerResultCache` and
`SegmentResultCache` swap it in by config knob with zero call-site
changes:

  get: L1 first; on miss ask L2 (when the key is shareable and the
       circuit allows); an L2 hit back-fills L1 so the next read is
       local. Hits annotate the active trace node with cacheTier.
  put: write-through — L1 always, L2 best-effort (failures feed the
       breaker and are invisible to the query).

`remote_key_fn(key) -> Optional[str]` maps the caller's tuple key to a
stable wire string, or None for keys that MUST stay local — segment
versions that are per-process generation stamps rather than content
CRCs would collide across instances, so they never leave the process.

Invalidation stays version-based: predicates run on L1 only; remote
entries for a replaced segment/epoch are already unaddressable under
their old key string and age out by TTL on the cache server.
"""
from __future__ import annotations

from typing import Callable, Hashable, Optional

from pinot_tpu.cache.core import LruTtlCache
from pinot_tpu.cache.remote import RemoteCacheBackend
from pinot_tpu.utils import tracing


class TieredCache:
    """L1 (local LruTtlCache) + L2 (RemoteCacheBackend) as one cache."""

    #: entries from this backend may come from a SHARED store: callers
    #: must encode/decode with the typed wire codec (cache/core.py
    #: wire_*), never pickle — a poisoned shared entry fed to
    #: pickle.loads would execute code on every replica. Any future
    #: remote-capable backend must set this flag too.
    wire_codec = True

    def __init__(self, l1: LruTtlCache, l2: RemoteCacheBackend,
                 remote_key_fn: Callable[[Hashable], Optional[str]],
                 l2_ttl_seconds: Optional[float] = None):
        self.l1 = l1
        self.l2 = l2
        self._remote_key = remote_key_fn
        #: TTL stamped on remote entries; defaults to the L1 budget so
        #: both tiers age together
        self.l2_ttl_seconds = (l1.ttl_seconds if l2_ttl_seconds is None
                               else float(l2_ttl_seconds))

    # ------------------------------------------------------------------
    def get(self, key: Hashable) -> Optional[bytes]:
        payload, _tier = self.get_with_tier(key)
        return payload

    def get_with_tier(self, key: Hashable):
        """(payload, tier) where tier is 'L1', 'L2' or None on miss."""
        payload = self.l1.get(key)
        if payload is not None:
            self._annotate("L1")
            return payload, "L1"
        rkey = self._remote_key(key)
        if rkey is not None:
            hit = self.l2.get_with_ttl(rkey)
            if hit is not None:
                payload, remaining = hit
                # back-fill L1 so the replica pays the RTT once — capped
                # at the entry's REMAINING L2 TTL: a fresh full L1 TTL
                # would stretch the staleness budget up to 2x (TTL is
                # the only freshness bound for cache_realtime tables)
                ttl = (self.l1.ttl_seconds if remaining is None
                       else min(self.l1.ttl_seconds, remaining))
                self.l1.put(key, payload, ttl_seconds=ttl)
                self._annotate("L2")
                return payload, "L2"
        return None, None

    def put(self, key: Hashable, payload: bytes) -> bool:
        ok = self.l1.put(key, payload)
        rkey = self._remote_key(key)
        if rkey is not None:
            self.l2.put(rkey, payload, ttl_seconds=self.l2_ttl_seconds)
        return ok

    @staticmethod
    def _annotate(tier: str) -> None:
        # L2 marks dominate: one remote hit in a request is the
        # interesting signal even when sibling segments hit L1
        if tier == "L2" or tracing.get_attr("cacheTier") is None:
            tracing.annotate(cacheTier=tier)

    # -- parity with LruTtlCache ---------------------------------------
    def invalidate(self, predicate: Callable[[Hashable], bool]) -> int:
        return self.l1.invalidate(predicate)

    def clear(self, remote: bool = False) -> None:
        """L1 always; the SHARED remote tier only on explicit request
        (benchmarks measuring cold-start) — a routine local clear must
        not cold-start every other replica."""
        self.l1.clear()
        if remote:
            self.l2.clear()

    @property
    def stats(self):
        return self.l1.stats

    @property
    def max_bytes(self) -> int:
        return self.l1.max_bytes

    @property
    def ttl_seconds(self) -> float:
        return self.l1.ttl_seconds

    @property
    def size_bytes(self) -> int:
        return self.l1.size_bytes

    def __len__(self) -> int:
        return len(self.l1)

    def close(self) -> None:
        self.l2.close()


def tiered_backend_from_config(config, tier_prefix: str, metric_prefix: str,
                               remote_key_fn, metrics=None,
                               labels=None) -> TieredCache:
    """One tier's L1+L2 from the shared config knobs — the single place
    both `BrokerResultCache.from_config` and
    `SegmentResultCache.from_config` assemble their tiered backend, so
    a new remote knob lands in both tiers at once.

    tier_prefix: the tier's key family (e.g. 'pinot.broker.result.cache'
    — supplies `.bytes`, `.ttl.seconds`, `.remote.address`); the client
    knobs under 'pinot.cache.remote.*' are shared by every mount.

    `.remote.address` may be a comma-separated list: with >= 2 addresses
    the L2 mount becomes a client-side consistent-hash ring
    (cache/ring.py) — per-node breakers, a dead node degrades only its
    key range to L1-only — so cache capacity scales horizontally with
    the fleet and one box is no longer a fabric SPOF."""
    l1 = LruTtlCache(config.get_int(f"{tier_prefix}.bytes"),
                     config.get_float(f"{tier_prefix}.ttl.seconds"),
                     metrics=metrics, metric_prefix=metric_prefix,
                     labels=labels)
    client_kwargs = dict(
        timeout_seconds=config.get_float(
            "pinot.cache.remote.timeout.seconds"),
        pool_size=config.get_int("pinot.cache.remote.pool.size"),
        failure_threshold=config.get_int(
            "pinot.cache.remote.breaker.failures"),
        reset_seconds=config.get_float(
            "pinot.cache.remote.breaker.reset.seconds"),
        metrics=metrics, labels=labels,
        compress_threshold=config.get_int(
            "pinot.cache.server.compress.threshold.bytes"))
    address = config.get_str(f"{tier_prefix}.remote.address")
    if "," in address:
        from pinot_tpu.cache.ring import RingRemoteCacheBackend
        l2 = RingRemoteCacheBackend(
            address.split(","),
            vnodes=config.get_int("pinot.cache.remote.ring.vnodes"),
            **client_kwargs)
    else:
        l2 = RemoteCacheBackend(address, **client_kwargs)
    return TieredCache(l1, l2, remote_key_fn)
