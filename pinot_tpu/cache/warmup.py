"""Segment warmup: replay recently cached query plans on segment load.

ROADMAP item delivered: a rollout of a fresh immutable segment should not
start cold. Every time the server caches a tier-2 partial it also logs
(table, plan fingerprint, canonical SQL) into a per-table recency log;
when a new immutable segment arrives, the warmup pass replays the logged
plans against JUST that segment — populating the segment cache (and,
through a tiered backend, the shared remote tier) AND proactively staging
the plans' columns into device HBM residency (ops/residency.py, under the
seeding context so admission favors them) — BEFORE the segment is
published for queries. The first routed query then hits tier 2 instead of
scanning, and even a cache-missing literal variant runs device-resident.

The log stores the SQL, not a parsed context: QueryContext is cheap to
rebuild, and SQL is the only representation that round-trips the plan
fingerprint exactly (fingerprint() is derived from the parsed tree).

Failure semantics: warmup is strictly best-effort — any per-plan error is
swallowed (the segment still loads, it just starts cold for that plan),
and the pass is bounded by `max_plans` so a hot table's log can't stall
segment rollout.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


class FingerprintLog:
    """Per-table bounded recency log: plan fingerprint -> canonical SQL.

    Re-recording an already-logged fingerprint refreshes its recency (an
    OrderedDict move-to-end), so the replay set tracks the CURRENT
    dashboard mix, not the first N plans ever seen.

    journal_path (ROADMAP item): an append-only JSON-lines journal of
    every record(), reloaded at construction — a RESTARTED server warms
    fresh segments from its pre-restart traffic instead of an empty log.
    The journal compacts to a snapshot of the live (bounded) plan set
    whenever it grows past journal_max_bytes, via atomic tmp+rename.
    Torn/corrupt journals degrade line-by-line to whatever parses (a
    half-written tail costs one plan, never the log); an unreadable file
    degrades to empty. Journal I/O failures are swallowed — persistence
    is an optimization, the in-memory log is the source of truth."""

    def __init__(self, max_plans_per_table: int = 64,
                 journal_path: Optional[str] = None,
                 journal_max_bytes: int = 1 << 20):
        self.max_plans_per_table = max(1, int(max_plans_per_table))
        self._tables: Dict[str, "OrderedDict[str, tuple]"] = {}
        self._lock = threading.Lock()
        self.journal_path = journal_path
        self.journal_max_bytes = max(4096, int(journal_max_bytes))
        #: kept-open append handle + in-memory size mirror: record() is
        #: on the query path, so it pays one buffered write + flush, not
        #: an open/close + getsize syscall pair per plan
        self._journal_file = None
        self._journal_bytes = 0
        if journal_path:
            self._replay_journal()

    # -- journal -------------------------------------------------------
    def _replay_journal(self) -> None:
        try:
            # errors="replace": a binary-garbage journal must degrade to
            # per-line JSON failures (skipped below), not a decode crash
            with open(self.journal_path, encoding="utf-8",
                      errors="replace") as f:
                lines = f.readlines()
        except OSError:
            return  # no journal yet (first boot) or unreadable: start cold
        for raw in lines:
            try:
                e = json.loads(raw)
                table, fp, sql = e["t"], e["f"], e["s"]
            except (ValueError, TypeError, KeyError):
                continue  # torn/corrupt line: skip it, keep the rest
            plans = self._tables.setdefault(table, OrderedDict())
            if fp in plans:
                plans.move_to_end(fp)
            plans[fp] = (sql, e.get("x"))
            while len(plans) > self.max_plans_per_table:
                plans.popitem(last=False)

    def _append_journal_locked(self, table: str, fingerprint: str,
                               sql: str, extra_filter) -> None:
        line = json.dumps({"t": table, "f": fingerprint, "s": sql,
                           "x": extra_filter}) + "\n"
        try:
            if self._journal_file is None:
                self._journal_file = open(self.journal_path, "a",
                                          encoding="utf-8")
                self._journal_bytes = os.path.getsize(self.journal_path)
            self._journal_file.write(line)
            self._journal_file.flush()  # torn tail = at most one line
            self._journal_bytes += len(line.encode("utf-8"))
            if self._journal_bytes > self.journal_max_bytes:
                self._compact_locked()
        except OSError:
            log.debug("fingerprint journal write failed", exc_info=True)

    def _compact_locked(self) -> None:
        """Rewrite the journal as a snapshot of the LIVE plan set (the
        bound already dropped everything else), atomically: a crash
        mid-compaction leaves either the old or the new file, never a
        mix."""
        if self._journal_file is not None:
            self._journal_file.close()
            self._journal_file = None
        tmp = self.journal_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for table, plans in self._tables.items():
                for fp, (sql, extra) in plans.items():
                    f.write(json.dumps({"t": table, "f": fp, "s": sql,
                                        "x": extra}) + "\n")
        os.replace(tmp, self.journal_path)
        self._journal_bytes = os.path.getsize(self.journal_path)

    def close(self) -> None:
        """Release the journal handle (in-memory state stays usable)."""
        with self._lock:
            if self._journal_file is not None:
                try:
                    self._journal_file.close()
                except OSError:
                    pass
                self._journal_file = None

    def record(self, table: str, fingerprint: str, sql: str,
               extra_filter: Optional[str] = None) -> None:
        """extra_filter: the hybrid time-boundary predicate that was
        ANDed into the plan server-side — the fingerprint covers the
        merged tree, so replay needs it to reproduce the same key."""
        with self._lock:
            plans = self._tables.setdefault(table, OrderedDict())
            if fingerprint in plans:
                plans.move_to_end(fingerprint)
            plans[fingerprint] = (sql, extra_filter)
            while len(plans) > self.max_plans_per_table:
                plans.popitem(last=False)
            if self.journal_path:
                self._append_journal_locked(table, fingerprint, sql,
                                            extra_filter)

    def plans(self, table: str) -> List[Tuple[str, str, Optional[str]]]:
        """[(fingerprint, sql, extra_filter)] most-recent-last."""
        with self._lock:
            return [(fp, sql, extra)
                    for fp, (sql, extra)
                    in self._tables.get(table, OrderedDict()).items()]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(p) for p in self._tables.values())


class SegmentWarmup:
    """The warmup pass: replay a table's logged plans on one segment."""

    def __init__(self, fingerprint_log: FingerprintLog, segment_cache,
                 max_plans: int = 32, use_tpu: bool = False,
                 engine_fn=None, metrics=None,
                 labels: Optional[dict] = None):
        """engine_fn: zero-arg callable returning the server's shared
        device engine (or None) — resolved lazily per warmup so the
        engine exists by the time segments start arriving."""
        self.log = fingerprint_log
        self.segment_cache = segment_cache
        self.max_plans = max(1, int(max_plans))
        self.use_tpu = use_tpu
        self._engine_fn = engine_fn
        self._metrics = metrics
        self._labels = labels
        #: local tallies (cheap asserts in tests)
        self.segments_warmed = 0
        self.entries_warmed = 0
        #: plans whose columns were prestaged into HBM residency for a
        #: NON-cacheable (upsert) segment — the seal pipeline's
        #: warm-before-swap evidence for tables the result cache skips
        self.segments_prestaged = 0

    def warm(self, table: str, segment: Any) -> int:
        """Replay logged plans against `segment`; returns entries warmed.
        Never raises — a failed warmup only costs cold-start."""
        from pinot_tpu.cache.segment_cache import (is_cacheable_segment,
                                                   is_cacheable_shape)
        from pinot_tpu.query.context import QueryContext
        from pinot_tpu.query.executor import QueryExecutor

        plans = self.log.plans(table)
        if not plans:
            return 0
        # result-cache warmup needs the cache; residency PRESTAGING does
        # not — a cache-disabled deployment still wants sealed segments'
        # columns in HBM before they publish (the zero-gap pipeline)
        cache_on = (self.segment_cache is not None
                    and self.segment_cache.enabled)
        cacheable = cache_on and is_cacheable_segment(segment)
        if not cache_on and self._engine_fn is None:
            return 0  # nothing to warm with
        warmed = 0
        # most recent plans first — when the budget cuts, keep the mix
        # dashboards are refreshing NOW
        for fingerprint, sql, extra_filter in reversed(
                plans[-self.max_plans:]):
            try:
                ctx = QueryContext.from_sql(sql)
                # the SAME merge the server execute path applies — the
                # fingerprint hashes the merged tree, so any divergence
                # would warm keys no routed query ever looks up
                from pinot_tpu.query.context import merge_extra_filter
                merge_extra_filter(ctx, extra_filter)
                if not is_cacheable_shape(ctx):
                    continue
                engine = self._engine_fn() if self._engine_fn else None
                if not cacheable:
                    # upsert segments never enter the result cache (their
                    # validity bitmap mutates in place), but their column
                    # + mask blocks still belong in HBM before the seal
                    # swap publishes them — the zero-gap pipeline's
                    # residency half applies regardless of cacheability
                    if engine is not None:
                        with engine.residency_seeding():
                            if engine.prestage([segment], ctx):
                                self.segments_prestaged += 1
                    continue
                if self.segment_cache.get(segment, fingerprint) is not None:
                    # already warm — an L2 hit here ALSO back-filled L1,
                    # which is exactly the rollout warmup we want. The
                    # DEVICE tier still starts cold on a result-cache
                    # hit, so stage the plan's columns into HBM anyway:
                    # literals drift, caches expire, and the resident
                    # columns are what survive both
                    warmed += 1
                    if engine is not None:
                        with engine.residency_seeding():
                            engine.prestage([segment], ctx)
                    continue
                ex = QueryExecutor([segment], use_tpu=self.use_tpu,
                                   engine=engine,
                                   segment_cache=self.segment_cache)
                if engine is not None:
                    # replayed plans ARE the FingerprintLog's evidence of
                    # per-segment plan traffic: staging done under the
                    # seeding context admits the columns into HBM
                    # residency with the frequency seed, so the fresh
                    # segment's first routed queries run device-resident
                    with engine.residency_seeding():
                        ex.execute_context(ctx)
                else:
                    ex.execute_context(ctx)
                if self.segment_cache.get(segment, fingerprint) is not None:
                    warmed += 1
            except Exception:  # noqa: BLE001 — warmup must never block load
                log.debug("warmup plan failed for %s on %s",
                          fingerprint, getattr(segment, "name", "?"),
                          exc_info=True)
        if warmed:
            self.segments_warmed += 1
            self.entries_warmed += warmed
            if self._metrics is not None:
                self._metrics.add_meter("segment_warmup_segments",
                                        labels=self._labels)
                self._metrics.add_meter("segment_warmup_entries", warmed,
                                        labels=self._labels)
        return warmed
