"""Python client library for the broker HTTP edge.

Reference parity: pinot-clients/pinot-java-client (broker Connection +
ResultSetGroup) and pinot-jdbc-client's cursor surface — a dependency-free
client users embed in applications:

    from pinot_tpu.client import connect
    conn = connect("localhost:8099")
    rs = conn.execute("SELECT COUNT(*) FROM events")
    rs.rows, rs.columns

    cur = conn.cursor()           # DB-API 2.0-style
    cur.execute("SELECT a, b FROM t WHERE a > %(lo)s", {"lo": 3})
    cur.fetchall()
"""
from pinot_tpu.client.connection import (Connection, Cursor, PinotClientError,
                                         ResultSet, connect)

__all__ = ["connect", "Connection", "Cursor", "ResultSet",
           "PinotClientError"]
