"""Broker HTTP client: Connection / ResultSet / DB-API-style Cursor.

Reference parity: pinot-clients/pinot-java-client
(Connection.execute -> ResultSetGroup over broker REST) and
pinot-clients/pinot-jdbc-client (statement/cursor surface). Transport is
the broker's POST /query/sql JSON edge (broker/http_api.py); no external
dependencies.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pinot_tpu.utils import errorcodes


class PinotClientError(Exception):
    """Query rejected or failed broker-side (carries the exceptions)."""

    def __init__(self, message: str, exceptions: Optional[list] = None):
        super().__init__(message)
        self.exceptions = exceptions or []


class PinotTimeoutError(PinotClientError):
    """The query exceeded its end-to-end deadline (broker errorCode 250).
    `result_set` carries whatever partial answer the broker assembled
    before the budget ran out (partialResult=true)."""

    def __init__(self, message: str, exceptions: Optional[list] = None,
                 result_set: Optional["ResultSet"] = None):
        super().__init__(message, exceptions)
        self.result_set = result_set


class PinotOverloadError(PinotClientError):
    """The fleet REFUSED the query at admission (errorCode 211,
    server-side overload protection) rather than running it into a
    deadline miss. ``retry_after_ms`` carries the server's drain hint
    (None when absent) — back off at least that long before retrying;
    ``result_set`` carries whatever partial answer other replicas
    assembled (partialResult=true)."""

    def __init__(self, message: str, exceptions: Optional[list] = None,
                 result_set: Optional["ResultSet"] = None):
        super().__init__(message, exceptions)
        self.result_set = result_set
        self.retry_after_ms: Optional[float] = None
        for x in self.exceptions:
            hint = errorcodes.parse_retry_after(x.get("message", ""))
            if hint is not None and (self.retry_after_ms is None
                                     or hint > self.retry_after_ms):
                self.retry_after_ms = hint


_TIMEOUT_ERROR_CODE = errorcodes.EXECUTION_TIMEOUT
_OVERLOAD_ERROR_CODE = errorcodes.SERVER_OVERLOADED


class ResultSet:
    def __init__(self, payload: dict):
        table = payload.get("resultTable") or {}
        schema = table.get("dataSchema") or {}
        self.columns: List[str] = schema.get("columnNames", [])
        self.column_types: List[str] = schema.get("columnDataTypes", [])
        self.rows: List[list] = table.get("rows", [])
        self.exceptions: List[dict] = payload.get("exceptions", [])
        #: broker-declared incompleteness: a server timed out or died and
        #: the rows above are only part of the answer
        self.partial_result: bool = bool(payload.get("partialResult"))
        self.stats: Dict[str, Any] = {
            k: v for k, v in payload.items()
            if k not in ("resultTable", "exceptions")}

    def __iter__(self):
        return iter(self.rows)

    def __len__(self) -> int:
        return len(self.rows)


class Connection:
    def __init__(self, broker: str, timeout: float = 60.0,
                 scheme: str = "http"):
        if "://" in broker:
            scheme, _, broker = broker.partition("://")
        self.base = f"{scheme}://{broker}"
        self.timeout = timeout

    # ------------------------------------------------------------------
    def execute(self, sql: str,
                params: Optional[Dict[str, Any]] = None,
                timeout_ms: Optional[float] = None) -> ResultSet:
        """Run SQL (with optional %(name)s parameter substitution — values
        are SQL-escaped client-side) and raise on broker exceptions.
        timeout_ms: per-query end-to-end budget, shipped as the broker's
        `SET timeoutMs` option AND used (plus grace) as the HTTP read
        timeout; a deadline miss raises PinotTimeoutError carrying the
        broker's partial result."""
        if params:
            # token-targeted replacement, NOT the % operator: a literal %
            # in the SQL (LIKE '%x%', modulo) must never be interpreted
            # as a format spec
            import re as _re
            quoted = {k: _quote(v) for k, v in params.items()}

            def _sub(m):
                key = m.group(1)
                if key not in quoted:
                    raise PinotClientError(f"missing parameter {key!r}")
                return quoted[key]

            sql = _re.sub(r"%\((\w+)\)s", _sub, sql)
        http_timeout = self.timeout
        if timeout_ms is not None:
            # leading SET statements are the option channel the broker
            # parser already speaks — no URL/body schema change needed
            sql = f"SET timeoutMs = {int(timeout_ms)}; {sql}"
            http_timeout = timeout_ms / 1000.0 + 5.0
        req = urllib.request.Request(
            f"{self.base}/query/sql",
            data=json.dumps({"sql": sql}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=http_timeout) as r:
                payload = json.loads(r.read())
        except urllib.error.URLError as e:
            raise PinotClientError(f"broker unreachable: {e}") from e
        rs = ResultSet(payload)
        if rs.exceptions:
            message = "; ".join(str(x.get("message", x))
                                for x in rs.exceptions)
            if any(x.get("errorCode") == _TIMEOUT_ERROR_CODE
                   for x in rs.exceptions):
                # typed miss: the partial rides along instead of vanishing
                raise PinotTimeoutError(message, rs.exceptions,
                                        result_set=rs)
            if any(x.get("errorCode") == _OVERLOAD_ERROR_CODE
                   for x in rs.exceptions):
                # typed shed: retry-after hint + partial ride along
                raise PinotOverloadError(message, rs.exceptions,
                                         result_set=rs)
            raise PinotClientError(message, rs.exceptions)
        return rs

    def cursor(self) -> "Cursor":
        return Cursor(self)

    def close(self) -> None:  # stateless HTTP; for DB-API symmetry
        pass

    # context manager
    def __enter__(self) -> "Connection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Cursor:
    """Minimal DB-API 2.0 cursor over Connection (ref jdbc-client)."""

    def __init__(self, conn: Connection):
        self._conn = conn
        self._rs: Optional[ResultSet] = None
        self._pos = 0

    @property
    def description(self) -> Optional[List[Tuple]]:
        if self._rs is None:
            return None
        return [(name, dtype, None, None, None, None, None)
                for name, dtype in zip(self._rs.columns,
                                       self._rs.column_types)]

    @property
    def rowcount(self) -> int:
        return -1 if self._rs is None else len(self._rs)

    def execute(self, sql: str,
                params: Optional[Dict[str, Any]] = None) -> "Cursor":
        self._rs = self._conn.execute(sql, params)
        self._pos = 0
        return self

    def fetchone(self) -> Optional[Sequence]:
        if self._rs is None or self._pos >= len(self._rs):
            return None
        row = self._rs.rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: int = 1) -> List[Sequence]:
        out = []
        for _ in range(size):
            row = self.fetchone()
            if row is None:
                break
            out.append(row)
        return out

    def fetchall(self) -> List[Sequence]:
        if self._rs is None:
            return []
        out = self._rs.rows[self._pos:]
        self._pos = len(self._rs)
        return out

    def close(self) -> None:
        self._rs = None


def _quote(v: Any) -> str:
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    return str(v)


def connect(broker: str, timeout: float = 60.0) -> Connection:
    """pinot-java-client ConnectionFactory.fromHostList analog."""
    return Connection(broker, timeout=timeout)
