"""Cluster assembly: control plane (controller-lite) + in-process clusters.

Reference parity: the Helix/ZooKeeper control plane (SURVEY.md L7) is
replaced by an in-process/JSON-backed ClusterState with callback watches —
ZK-free first, per the build plan (SURVEY.md §7.4).
"""
