"""MiniCluster: N servers + broker in one process, over real TCP.

Reference parity: the embedded-cluster integration harness —
pinot-integration-test-base ClusterTest.java:92 (startBrokers:186,
startServers:258 — real ZK + roles in one JVM). Here: real sockets, real
wire serde, no ZK; segment assignment is direct (the controller-lite
assignment strategies layer on top, pinot_tpu/controller).
"""
from __future__ import annotations

import os
import shutil
import tempfile
from typing import Dict, List, Optional, Sequence

from pinot_tpu.broker.http_api import BrokerHttpServer
from pinot_tpu.broker.request_handler import BrokerRequestHandler
from pinot_tpu.broker.routing import (
    BrokerRoutingManager, RoutingTable, SegmentInfo, TableRoute)
from pinot_tpu.segment.loader import ImmutableSegment
from pinot_tpu.server.data_manager import InstanceDataManager
from pinot_tpu.server.query_server import (
    QueryServer, ServerConnection, ServerQueryExecutor)


class MiniClusterServer:
    def __init__(self, instance_id: str, use_tpu: bool = False, config=None):
        self.instance_id = instance_id
        self.data_manager = InstanceDataManager(instance_id)
        self.executor = ServerQueryExecutor(self.data_manager,
                                            use_tpu=use_tpu, config=config)
        # honor the worker-pool/scheduler knobs like the real ServerRole
        # does (the overload bench sizes capacity through them; defaults
        # match QueryServer's own)
        from pinot_tpu.utils.config import PinotConfiguration as _PC
        _cfg = config or _PC()
        self.transport = QueryServer(
            self.executor,
            num_threads=_cfg.get_int("pinot.server.query.num.threads"),
            scheduler=_cfg.get_str("pinot.server.query.scheduler"))
        # multi-stage worker endpoint (mailbox data plane + stage executor);
        # leaf aggregates route through the single-stage executor and its
        # shared device engine (ref QueryRunner.java:258)
        from pinot_tpu.mse.dispatcher import (
            make_leaf_query_fn, make_scan_fn, make_segment_versions_fn)
        from pinot_tpu.mse.runtime import MseWorker
        from pinot_tpu.mse.stage_cache import StageOutputCache
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.metrics import get_registry
        engine_fn = self.executor._shared_engine if use_tpu else None
        stage_cache = StageOutputCache.from_config(
            config or PinotConfiguration(),
            metrics=get_registry("server"), labels={"instance": instance_id})
        self.mse_worker = MseWorker(
            instance_id,
            make_scan_fn(self.data_manager, engine_fn=engine_fn),
            leaf_query_fn=make_leaf_query_fn(self.data_manager, engine_fn),
            stage_cache=stage_cache,
            segment_versions_fn=make_segment_versions_fn(self.data_manager),
            config=config)

    def start(self) -> None:
        self.transport.start()
        self.mse_worker.start()

    def stop(self) -> None:
        self.mse_worker.stop()
        if self.mse_worker.stage_cache is not None:
            self.mse_worker.stage_cache.close()
        self.transport.stop()
        self.data_manager.shutdown()
        self.executor.segment_cache.close()
        self.executor.fingerprint_log.close()

    @property
    def address(self) -> str:
        return f"{self.transport.host}:{self.transport.port}"


class MiniCluster:
    #: fast-cycle task-fabric knobs for the embedded harness; any key
    #: the caller's config explicitly sets (override or properties file)
    #: wins over these
    MINION_DEFAULTS = {
        "pinot.minion.poll.seconds": 0.05,
        "pinot.minion.heartbeat.seconds": 0.25,
        "pinot.controller.task.lease.seconds": 2.0,
        "pinot.controller.task.retry.backoff.seconds": 0.1,
        "pinot.controller.task.retry.backoff.cap.seconds": 1.0,
        "pinot.controller.task.frequency.seconds": 0.5,
        # embedded clusters submit tasks explicitly; the generator scan
        # stays opt-in so tests control exactly what runs
        "pinot.controller.task.generators.enabled": False,
    }

    def __init__(self, num_servers: int = 2, use_tpu: bool = False,
                 result_cache: bool = False, num_brokers: int = 1,
                 cache_server: bool = False, config=None, chaos=None,
                 minions: int = 0, cache_servers: int = 0):
        """cache_server: start an in-process CacheServer (the remote L2
        role) and point every tier at it — brokers' result caches and
        servers' segment caches become `tiered` automatically, so
        replicas warm each other (cache/remote.py). cache_servers: start
        N >= 2 cache-server roles instead and mount them as a client-side
        consistent-hash ring (cache/ring.py) — one node's death degrades
        only its key range to L1-only. config: a base
        PinotConfiguration; cache_server(s) layer the fabric knobs on
        top of it. chaos: a utils.failpoints.FaultSchedule (or a plain
        [(site, policy-kwargs), ...] list) armed at start() and disarmed
        at stop() — deterministic fault injection for the whole cluster's
        deadline / hedge / retry paths. minions: start N MinionWorker
        roles plus the controller-side task fabric (ClusterState +
        TaskManager + a real CoordinationServer over TCP) and a tempdir
        deep store — submit_task()/wait_task() drive merge-rollup /
        purge / realtime-to-offline tasks end to end, with committed
        swaps applied to the embedded servers, routing, and caches."""
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.failpoints import FaultSchedule
        self.chaos: Optional[FaultSchedule] = None
        if chaos is not None:
            self.chaos = (chaos if isinstance(chaos, FaultSchedule)
                          else FaultSchedule(list(chaos)))
        self.cache_server = None
        self.cache_servers: List = []
        self._num_minions = max(0, int(minions))
        if self._num_minions:
            cfg = config or PinotConfiguration()
            # defaults only for keys the caller didn't set explicitly
            config = cfg.with_overrides({
                k: v for k, v in self.MINION_DEFAULTS.items()
                if not cfg.is_set(k)})
        overrides = {}
        n_cache = max(int(cache_servers), 1 if cache_server else 0)
        if n_cache:
            from pinot_tpu.cache.remote import CacheServer
            from pinot_tpu.utils.metrics import get_registry
            for _ in range(n_cache):
                cs = CacheServer(metrics=get_registry("cache_server"))
                cs.start()
                self.cache_servers.append(cs)
            #: back-compat alias: the single-server fabric's handle
            self.cache_server = self.cache_servers[0]
            address = ",".join(cs.address for cs in self.cache_servers)
            overrides = {
                "pinot.server.segment.cache.backend": "tiered",
                "pinot.server.segment.cache.remote.address": address,
                "pinot.broker.result.cache.backend": "tiered",
                "pinot.broker.result.cache.remote.address": address,
                "pinot.server.mse.stage.cache.backend": "tiered",
                "pinot.server.mse.stage.cache.remote.address": address,
            }
        if overrides:
            config = (config or PinotConfiguration()).with_overrides(overrides)
        self.config = config
        self.servers: List[MiniClusterServer] = [
            MiniClusterServer(f"server_{i}", use_tpu=use_tpu, config=config)
            for i in range(num_servers)]
        self.routing = BrokerRoutingManager()
        self._connections: Dict[str, ServerConnection] = {}
        self.broker: Optional[BrokerRequestHandler] = None
        self.brokers: List[BrokerRequestHandler] = []
        self._num_brokers = max(1, int(num_brokers))
        self.http: Optional[BrokerHttpServer] = None
        self._routes: Dict[str, RoutingTable] = {}
        #: per-table partition-pruning metadata (add_table stamps it on
        #: every later add_segment's SegmentInfo)
        self._table_meta: Dict[str, dict] = {}
        #: logical table -> tenant tag, replayed onto brokers at start()
        self._tenants: Dict[str, str] = {}
        #: table -> (segment-version token, (workers, peers)) memo for
        #: the MSE placement walk (see _mse_placement)
        self._mse_placement_memo: Dict[str, tuple] = {}
        #: opt-in tier-1 broker result cache (cache/broker_cache.py)
        self._result_cache_enabled = result_cache
        # -- controller-lite state (always on: the rebalance/repair
        # engine and the task fabric both diff against it) -------------
        from pinot_tpu.controller.cluster_state import (ClusterState,
                                                        InstanceState)
        self.cluster_state = ClusterState()
        for s in self.servers:
            self.cluster_state.register_instance(
                InstanceState(s.instance_id))
        #: instance_id -> wall-clock kill time; feeds heartbeat_ages()
        #: so the repair checker sees a killed server's age grow
        self._killed: Dict[str, float] = {}
        # -- minion task fabric (ISSUE 5) ------------------------------
        self.task_manager = None
        self.coordination = None
        self.minions: List = []
        self._minion_tmp: Optional[str] = None
        if self._num_minions:
            from pinot_tpu.controller.task_manager import TaskManager
            self._minion_tmp = tempfile.mkdtemp(prefix="pinot_tpu_fabric_")
            self.deep_store_uri = \
                f"file://{os.path.join(self._minion_tmp, 'store')}"
            self.task_manager = TaskManager(
                self.cluster_state, config=self.config,
                journal_path=os.path.join(self._minion_tmp,
                                          "tasks.journal"),
                on_replace=self._apply_replacement)

    # ------------------------------------------------------------------
    def _make_result_cache(self):
        if not self._result_cache_enabled:
            return None
        from pinot_tpu.cache.broker_cache import BrokerResultCache
        from pinot_tpu.utils.metrics import get_registry
        if self.config is not None:
            cfg = self.config.with_overrides(
                {"pinot.broker.result.cache.enabled": True})
            return BrokerResultCache.from_config(
                cfg, metrics=get_registry("broker"))
        return BrokerResultCache(metrics=get_registry("broker"))

    def start(self, with_http: bool = False) -> None:
        if self.chaos is not None:
            self.chaos.arm()
        for s in self.servers:
            s.start()
            self._connections[s.instance_id] = ServerConnection(
                s.transport.host, s.transport.port)
        from pinot_tpu.mse.dispatcher import QueryDispatcher
        self.mse = QueryDispatcher(
            workers={s.instance_id: s.mse_worker for s in self.servers},
            catalog_fn=self._catalog,
            table_workers_fn=self._table_workers,
            config=self.config,
            hedge_peers_fn=self._mse_hedge_peers)
        # N broker replicas over the SAME routing view and server
        # connections — each with its own (L1) result cache, sharing L2
        # through the cache server when one is running
        self.brokers = [
            BrokerRequestHandler(self.routing, self._connections,
                                 mse_dispatcher=self.mse,
                                 result_cache=self._make_result_cache(),
                                 config=self.config)
            for _ in range(self._num_brokers)]
        self.broker = self.brokers[0]
        # tenant tags for tables registered before start(): brokers did
        # not exist yet, replay the map onto the fresh handlers
        for table, tenant in self._tenants.items():
            for b in self.brokers:
                b.tenants[table] = tenant
                if b.quota_manager is not None:
                    b.quota_manager.set_table_tenant(table, tenant)
        if with_http:
            self.http = BrokerHttpServer(self.broker)
            self.http.start()
        if self._num_minions:
            # the fabric is REAL wire: a CoordinationServer over TCP and
            # worker clients speaking netframe lease/heartbeat/commit ops
            from pinot_tpu.controller.coordination import CoordinationServer
            from pinot_tpu.minion.worker import MinionWorker
            self.coordination = CoordinationServer(
                self.cluster_state, deep_store_uri=self.deep_store_uri,
                task_manager=self.task_manager)
            self.coordination.start()
            self.task_manager.start()
            for i in range(self._num_minions):
                w = MinionWorker(
                    f"minion_{i}", self.coordination.address,
                    work_dir=os.path.join(self._minion_tmp, f"minion_{i}"),
                    config=self.config)
                w.start()
                self.minions.append(w)

    def stop(self) -> None:
        for w in self.minions:
            w.stop()
        self.minions = []
        if self.task_manager is not None:
            self.task_manager.stop()
        if self.coordination is not None:
            self.coordination.stop()
            self.coordination = None
        if self.http is not None:
            self.http.stop()
        if getattr(self, "mse", None) is not None:
            self.mse.stop()
        for c in self._connections.values():
            c.close()
        for b in self.brokers:
            if b.result_cache is not None:
                b.result_cache.close()
        for s in self.servers:
            s.stop()
        for cs in self.cache_servers:
            cs.stop()
        self.cache_servers = []
        self.cache_server = None
        if self.chaos is not None:
            self.chaos.disarm()
        if self._minion_tmp is not None:
            shutil.rmtree(self._minion_tmp, ignore_errors=True)
            self._minion_tmp = None

    # -- multi-stage catalog / placement ------------------------------------
    def _catalog(self):
        """Logical table -> column names, unioned over all servers."""
        from pinot_tpu.models import base_table_name
        cat = {}
        for s in self.servers:
            dm = s.data_manager
            for phys in dm.table_names:
                logical = base_table_name(phys)
                tdm = dm.table(phys, create=False)
                sdms = tdm.acquire_segments(None)
                try:
                    if sdms:
                        cat.setdefault(logical,
                                       list(sdms[0].segment.column_names))
                finally:
                    type(tdm).release_all(sdms)
        return cat

    def _mse_placement(self, table: str):
        """(leaf workers, peers) for a logical table: servers with an
        IDENTICAL local segment view collapse to one leaf worker (each
        MSE leaf instance scans its WHOLE local view, so routing two
        full replicas would double every row) and the collapsed twins
        become that worker's hedge peers — re-issuing the stage there
        is row-identical by construction.

        Memoized on the hosting tables' segment-set VERSIONS (bumped by
        every add/remove), so the per-query dispatch path pays a few
        integer reads, not a full-cluster segment walk; a host whose
        table is registered but EMPTY still counts as a worker (its
        leaf scans nothing — an empty result, not a routing error)."""
        wanted = (table, table + "_OFFLINE", table + "_REALTIME")
        token = []
        for s in self.servers:
            for phys in s.data_manager.table_names:
                if phys in wanted:
                    tdm = s.data_manager.table(phys, create=False)
                    token.append((s.instance_id, phys, tdm.version))
        token = tuple(token)
        alive_by_id = {s.instance_id: s.mse_worker.alive
                       for s in self.servers}
        memo = self._mse_placement_memo.get(table)
        if memo is not None and memo[0] == token \
                and all(alive_by_id.get(w) for w in memo[1][0]):
            return memo[1]
        views = []
        alive = alive_by_id
        for s in self.servers:
            names = set()
            hosts = False
            for phys in s.data_manager.table_names:
                if phys not in wanted:
                    continue
                hosts = True
                tdm = s.data_manager.table(phys, create=False)
                sdms = tdm.acquire_segments(None)
                try:
                    names |= {f"{phys}:{x.segment.name}" for x in sdms}
                finally:
                    type(tdm).release_all(sdms)
            if hosts:
                views.append((s.instance_id, frozenset(names)))
        # one representative per distinct view, ALIVE members first: a
        # chaos-killed representative must not strand its alive twins
        # behind it (the query routes to a surviving copy; the dead one
        # simply can't be a hedge target either)
        by_view: Dict[frozenset, List[str]] = {}
        for inst, view in views:
            by_view.setdefault(view, []).append(inst)
        workers: List[str] = []
        peers: Dict[str, List[str]] = {}
        for inst, view in views:  # preserve server order of groups
            group = by_view[view]
            if group[0] != inst:
                continue  # not the group's first member: handled once
            rep = next((m for m in group if alive[m]), group[0])
            workers.append(rep)
            peers[rep] = [m for m in group if m != rep]
        # aliveness feeds the choice but NOT the memo token (it can
        # flip without a segment mutation) — so only memoize when every
        # hosting member is alive; degraded states recompute
        result = (workers, peers)
        if all(alive[inst] for inst, _v in views):
            self._mse_placement_memo[table] = (token, result)
        else:
            self._mse_placement_memo.pop(table, None)
        return result

    def _table_workers(self, table: str):
        """Servers hosting at least one segment of the (logical) table,
        full-replica twins collapsed (see _mse_placement)."""
        workers, _peers = self._mse_placement(table)
        if not workers:
            raise ValueError(f"no servers host table {table!r}")
        return workers

    def _mse_hedge_peers(self, table: str, instance: str) -> List[str]:
        """Alternate instances whose local segment view for the table is
        identical to `instance`'s — the legal stage-hedge targets."""
        _workers, peers = self._mse_placement(table)
        return peers.get(instance, [])

    # ------------------------------------------------------------------
    def add_table(self, table_name: str, table_type: str = "OFFLINE",
                  time_column: Optional[str] = None,
                  time_boundary: Optional[int] = None,
                  table_config=None, schema=None,
                  num_replica_groups: int = 0,
                  partition_column: Optional[str] = None,
                  num_partitions: int = 0,
                  tenant: Optional[str] = None,
                  tenant_weight: Optional[float] = None) -> None:
        """table_config/schema: required for minion tasks over the table
        (executors rebuild segments from the schema); mirrored into the
        fabric's ClusterState when the cluster runs minions.
        num_replica_groups >= 2 makes the table replica-group routed
        (each add_segment's [server_idx, *replicas] order IS the group
        order); partition_column/num_partitions stamp partition-pruning
        metadata on subsequent add_segment calls; tenant/tenant_weight
        tag the table for quota + weighted-fair scheduling (defaults
        from table_config when one is given)."""
        if table_config is not None:
            num_replica_groups = (num_replica_groups
                                  or table_config.routing.num_replica_groups)
            partition_column = (partition_column
                                or table_config.routing.partition_column)
            tenant = tenant or table_config.tenants.server
            if tenant_weight is None:
                tenant_weight = table_config.tenants.weight
        rt = self._routes.get(table_name)
        if rt is None:
            rt = RoutingTable()
            self._routes[table_name] = rt
        route = TableRoute(f"{table_name}_{table_type}",
                           time_column=time_column,
                           num_replica_groups=num_replica_groups)
        if table_type == "OFFLINE":
            rt.offline = route
        else:
            rt.realtime = route
        if time_boundary is not None:
            rt.time_boundary = time_boundary
        self.routing.set_route(table_name, rt)
        self._table_meta[table_name] = {
            "partition_column": partition_column,
            "num_partitions": int(num_partitions or 0),
        }
        if tenant:
            for b in self.brokers:
                b.tenants[table_name] = tenant
                if b.quota_manager is not None:
                    b.quota_manager.set_table_tenant(table_name, tenant)
            self._tenants[table_name] = tenant
            if tenant_weight is not None:
                for s in self.servers:
                    sched = s.transport.scheduler
                    if hasattr(sched, "set_tenant_weight"):
                        sched.set_tenant_weight(tenant, tenant_weight)
        if self.cluster_state is not None and table_config is not None \
                and schema is not None:
            self.cluster_state.add_table(table_config, schema)

    def add_segment(self, table_name: str, segment: ImmutableSegment,
                    server_idx: int, table_type: str = "OFFLINE",
                    replicas: Sequence[int] = (),
                    partition_id: Optional[int] = None) -> None:
        """Load the segment on server_idx (+replicas) and register
        routing. For replica-group tables the [server_idx, *replicas]
        ORDER is the group order (element g lives in group g)."""
        physical = f"{table_name}_{table_type}"
        targets = [server_idx, *replicas]
        for idx in targets:
            self.servers[idx].data_manager.table(physical).add_segment(segment)
        rt = self._routes[table_name]
        route = rt.offline if table_type == "OFFLINE" else rt.realtime
        meta = segment.metadata
        tmeta = self._table_meta.get(table_name, {})
        route.segments[segment.name] = SegmentInfo(
            name=segment.name,
            servers=[self.servers[i].instance_id for i in targets],
            partition_id=partition_id,
            partition_column=(tmeta.get("partition_column")
                              if partition_id is not None else None),
            num_partitions=(tmeta.get("num_partitions", 0)
                            if partition_id is not None else 0),
            start_time=meta.start_time, end_time=meta.end_time,
            version=meta.crc)
        if self.cluster_state is not None:
            # mirror into the fabric's state so generators see the
            # segment and task executors can localize it by dir_path
            from pinot_tpu.controller.cluster_state import SegmentState
            self.cluster_state.upsert_segment(SegmentState(
                name=segment.name, table=physical,
                instances=[self.servers[i].instance_id for i in targets],
                dir_path=segment.dir.path, num_docs=segment.num_docs,
                start_time=meta.start_time, end_time=meta.end_time,
                partition_id=partition_id, crc=meta.crc))

    def remove_segment(self, table_name: str, segment_name: str,
                       table_type: str = "OFFLINE") -> None:
        """Unload from every server and drop from routing (bumps the
        routing epoch, so tier-1 cache entries go unaddressable)."""
        physical = f"{table_name}_{table_type}"
        for s in self.servers:
            tdm = s.data_manager.table(physical, create=False)
            if tdm is not None:
                tdm.remove_segment(segment_name)
        rt = self._routes.get(table_name)
        route = None if rt is None else (
            rt.offline if table_type == "OFFLINE" else rt.realtime)
        if route is not None:
            route.segments.pop(segment_name, None)
        if self.cluster_state is not None:
            self.cluster_state.remove_segment(
                f"{table_name}_{table_type}", segment_name)

    def kill_server(self, idx: int) -> None:
        """SIGKILL-equivalent for one embedded server: the query
        transport (and MSE worker) die mid-whatever with no goodbye —
        established broker channels sever, new dials are refused — while
        the data manager's memory is simply abandoned, exactly the state
        a killed process leaves. Brokers discover it the hard way
        (connection error -> failure detector -> group demotion).
        Idempotent; `query_server.QueryServer.stop` tolerates repeats."""
        import time as _time
        s = self.servers[idx]
        s.mse_worker.stop()
        s.transport.stop()
        self._killed.setdefault(s.instance_id, _time.time())

    def kill_replica_group(self, table_name: str, group: int,
                           table_type: str = "OFFLINE") -> List[str]:
        """Kill EVERY member of one replica group (the whole-rack chaos
        scenario). Returns the instance ids killed."""
        rt = self._routes[table_name]
        route = rt.offline if table_type == "OFFLINE" else rt.realtime
        members = {seg.servers[group] for seg in route.segments.values()
                   if group < len(seg.servers)}
        by_id = {s.instance_id: i for i, s in enumerate(self.servers)}
        for m in sorted(members):
            self.kill_server(by_id[m])
        return sorted(members)

    def query(self, sql: str):
        assert self.broker is not None, "cluster not started"
        return self.broker.handle(sql)

    # -- minion task fabric --------------------------------------------
    def submit_task(self, task) -> dict:
        """Submit a TaskConfig to the fabric's queue; a minion worker
        leases and runs it. Returns the queued entry (dict)."""
        assert self.task_manager is not None, \
            "MiniCluster(minions=N) required for background tasks"
        return self.task_manager.submit(task).to_dict()

    def task(self, task_id: str) -> Optional[dict]:
        e = self.task_manager.queue.get(task_id)
        return e.to_dict() if e is not None else None

    def wait_task(self, task_id: str, timeout_s: float = 30.0) -> dict:
        """Block until the task reaches a terminal state (COMPLETED /
        FAILED / CANCELLED) or raise on timeout."""
        import time as _time
        from pinot_tpu.controller.task_manager import TERMINAL
        deadline = _time.time() + timeout_s
        while _time.time() < deadline:
            e = self.task(task_id)
            if e is not None and e["state"] in TERMINAL:
                return e
            _time.sleep(0.02)
        raise TimeoutError(
            f"task {task_id} not terminal after {timeout_s}s: "
            f"{self.task(task_id)}")

    def _apply_replacement(self, adds, removes) -> None:
        """Push a committed segment swap into the embedded cluster: load
        + WARM the new segments on their target servers first (warmup
        replays logged plans before the segment is routable), then swap
        each affected route's segment dict atomically (one reference
        assignment — queries see the old or the new set, never half),
        then unload retired segments and drop the brokers' negative-
        cache entries for the table. The routing epoch moves with the
        swap, so whole-result/partial cache entries for the old set go
        unaddressable by construction."""
        from pinot_tpu.broker.routing import TableRoute, _ObservedSegments
        from pinot_tpu.models import split_physical_table_name
        from pinot_tpu.segment.fs import localize_segment
        from pinot_tpu.segment.loader import load_segment
        id_to_server = {s.instance_id: s for s in self.servers}
        by_route: Dict[tuple, dict] = {}

        def split(physical: str) -> tuple:
            logical, ttype = split_physical_table_name(physical)
            return logical, ttype or "OFFLINE"

        for st in adds:
            local = localize_segment(
                st.dir_path,
                os.path.join(self._minion_tmp, "localized", st.table))
            seg = load_segment(local)
            servers = [id_to_server[i] for i in st.instances
                       if i in id_to_server] or [self.servers[0]]
            for srv in servers:
                srv.data_manager.table(st.table).add_segment(seg)
            ops = by_route.setdefault(split(st.table), {"add": [], "rm": []})
            ops["add"].append(SegmentInfo(
                name=st.name, servers=[s.instance_id for s in servers],
                start_time=st.start_time, end_time=st.end_time,
                version=st.crc))
        for table, name in removes:
            by_route.setdefault(split(table), {"add": [], "rm": []})[
                "rm"].append(name)
        for (logical, ttype), ops in by_route.items():
            rt = self._routes.get(logical)
            if rt is None:
                rt = RoutingTable()
                self._routes[logical] = rt
            physical = f"{logical}_{ttype}"
            route = rt.offline if ttype == "OFFLINE" else rt.realtime
            if route is None:
                cfg = (self.cluster_state.tables.get(logical)
                       if self.cluster_state is not None else None)
                route = TableRoute(
                    physical,
                    time_column=cfg.retention.time_column if cfg else None)
                if ttype == "OFFLINE":
                    rt.offline = route
                else:
                    rt.realtime = route
                self.routing.set_route(logical, rt)  # reset suffix views
            # atomic swap: build the post-swap dict, then ONE reference
            # assignment + counter bump (epoch memo invalidation)
            snap = dict(route.segments)
            for name in ops["rm"]:
                snap.pop(name, None)
            for info in ops["add"]:
                snap[info.name] = info
            route.segments = _ObservedSegments(route, snap)
            route.mutation_version = next(route._mut_counter)
        for table, name in removes:
            for srv in self.servers:
                tdm = srv.data_manager.table(table, create=False)
                if tdm is not None:
                    tdm.remove_segment(name)
        for logical, _ttype in by_route:
            for b in self.brokers:
                b.on_segments_replaced(logical)

    # -- self-healing maintenance (ISSUE 18) ---------------------------
    def heartbeat_ages(self) -> Dict[str, float]:
        """Instance -> heartbeat age (seconds). Embedded servers don't
        heartbeat over a wire; a live server's age is 0.0 and a killed
        one's age is the wall-clock time since kill_server() — exactly
        the signal shape RepairChecker debounces on."""
        import time as _time
        now = _time.time()
        return {s.instance_id: (now - self._killed[s.instance_id]
                                if s.instance_id in self._killed else 0.0)
                for s in self.servers}

    def make_rebalancer(self, config=None, journal_path=None):
        """A Rebalancer wired to the embedded servers: load = load+warm
        the segment dir on the target's data manager, commit = flip
        ClusterState assignment AND the broker routes atomically, unload
        = drop from the source's data manager, live = not killed."""
        from pinot_tpu.controller.rebalancer import Rebalancer
        from pinot_tpu.segment.loader import load_segment
        id_to_server = {s.instance_id: s for s in self.servers}

        def load(instance_id, table, st):
            if st is None or not st.dir_path:
                return
            srv = id_to_server[instance_id]
            tdm = srv.data_manager.table(table)
            if tdm.current_segment(st.name) is not None:
                return  # idempotent resume: already loaded+warmed
            tdm.add_segment(load_segment(st.dir_path))

        def unload(instance_id, table, name):
            srv = id_to_server.get(instance_id)
            if srv is None:
                return
            tdm = srv.data_manager.table(table, create=False)
            if tdm is not None:
                tdm.remove_segment(name)

        def commit(table, assignment):
            self.cluster_state.commit_moves(table, assignment)
            self._commit_routes(table, assignment)

        rb = Rebalancer(self.cluster_state, load_fn=load, unload_fn=unload,
                        commit_fn=commit,
                        live_fn=lambda iid: iid not in self._killed,
                        config=config if config is not None else self.config,
                        journal_path=journal_path)
        # embedded brokers route from point-in-time snapshots; give
        # in-flight queries planned pre-commit a beat before the source
        # stops serving (data_manager silently skips missing segments)
        rb.drain_grace_s = 0.05
        return rb

    def make_repair_checker(self, rebalancer, config=None):
        from pinot_tpu.controller.repair import RepairChecker
        return RepairChecker(self.cluster_state, rebalancer,
                             self.heartbeat_ages,
                             config=config if config is not None
                             else self.config)

    def _commit_routes(self, physical: str,
                       assignment: Dict[str, List[str]]) -> None:
        """Mirror a committed assignment into broker routing with the
        _apply_replacement atomic-swap discipline: ONE reference
        assignment per route + a mutation bump, then negative-cache
        invalidation — queries see the old or the new replica set,
        never half a batch."""
        import dataclasses
        from pinot_tpu.broker.routing import _ObservedSegments
        from pinot_tpu.models import split_physical_table_name
        logical, ttype = split_physical_table_name(physical)
        rt = self._routes.get(logical)
        route = None if rt is None else (
            rt.offline if (ttype or "OFFLINE") == "OFFLINE" else rt.realtime)
        if route is None:
            return
        snap = dict(route.segments)
        changed = False
        for name, insts in assignment.items():
            info = snap.get(name)
            if info is not None:
                snap[name] = dataclasses.replace(info, servers=list(insts))
                changed = True
        if not changed:
            return
        route.segments = _ObservedSegments(route, snap)
        route.mutation_version = next(route._mut_counter)
        for b in self.brokers:
            b.on_segments_replaced(logical)

    def run_retention(self, now_ms=None) -> Dict[str, List[str]]:
        """Close the retention loop end to end: purge expired segments
        from ClusterState, then actually unload them from every server,
        drop them from routing (epoch bump), and invalidate broker
        caches — expired data stops being served AND its cache entries
        go unaddressable, in one call."""
        from pinot_tpu.controller import maintenance
        from pinot_tpu.models import split_physical_table_name
        removed: Dict[str, List[str]] = {}
        for seg in maintenance.run_retention(self.cluster_state,
                                             now_ms=now_ms):
            removed.setdefault(seg.table, []).append(seg.name)
        for physical, names in removed.items():
            logical, ttype = split_physical_table_name(physical)
            for name in names:
                self.remove_segment(logical, name, ttype or "OFFLINE")
            for b in self.brokers:
                b.on_segments_replaced(logical)
        return removed
