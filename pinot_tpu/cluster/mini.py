"""MiniCluster: N servers + broker in one process, over real TCP.

Reference parity: the embedded-cluster integration harness —
pinot-integration-test-base ClusterTest.java:92 (startBrokers:186,
startServers:258 — real ZK + roles in one JVM). Here: real sockets, real
wire serde, no ZK; segment assignment is direct (the controller-lite
assignment strategies layer on top, pinot_tpu/controller).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from pinot_tpu.broker.http_api import BrokerHttpServer
from pinot_tpu.broker.request_handler import BrokerRequestHandler
from pinot_tpu.broker.routing import (
    BrokerRoutingManager, RoutingTable, SegmentInfo, TableRoute)
from pinot_tpu.segment.loader import ImmutableSegment
from pinot_tpu.server.data_manager import InstanceDataManager
from pinot_tpu.server.query_server import (
    QueryServer, ServerConnection, ServerQueryExecutor)


class MiniClusterServer:
    def __init__(self, instance_id: str, use_tpu: bool = False, config=None):
        self.instance_id = instance_id
        self.data_manager = InstanceDataManager(instance_id)
        self.executor = ServerQueryExecutor(self.data_manager,
                                            use_tpu=use_tpu, config=config)
        self.transport = QueryServer(self.executor)
        # multi-stage worker endpoint (mailbox data plane + stage executor);
        # leaf aggregates route through the single-stage executor and its
        # shared device engine (ref QueryRunner.java:258)
        from pinot_tpu.mse.dispatcher import make_leaf_query_fn, make_scan_fn
        from pinot_tpu.mse.runtime import MseWorker
        engine_fn = self.executor._shared_engine if use_tpu else None
        self.mse_worker = MseWorker(
            instance_id,
            make_scan_fn(self.data_manager, engine_fn=engine_fn),
            leaf_query_fn=make_leaf_query_fn(self.data_manager, engine_fn))

    def start(self) -> None:
        self.transport.start()
        self.mse_worker.start()

    def stop(self) -> None:
        self.mse_worker.stop()
        self.transport.stop()
        self.data_manager.shutdown()
        self.executor.segment_cache.close()
        self.executor.fingerprint_log.close()

    @property
    def address(self) -> str:
        return f"{self.transport.host}:{self.transport.port}"


class MiniCluster:
    def __init__(self, num_servers: int = 2, use_tpu: bool = False,
                 result_cache: bool = False, num_brokers: int = 1,
                 cache_server: bool = False, config=None, chaos=None):
        """cache_server: start an in-process CacheServer (the remote L2
        role) and point every tier at it — brokers' result caches and
        servers' segment caches become `tiered` automatically, so
        replicas warm each other (cache/remote.py). config: a base
        PinotConfiguration; cache_server=True layers the fabric knobs on
        top of it. chaos: a utils.failpoints.FaultSchedule (or a plain
        [(site, policy-kwargs), ...] list) armed at start() and disarmed
        at stop() — deterministic fault injection for the whole cluster's
        deadline / hedge / retry paths."""
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.failpoints import FaultSchedule
        self.chaos: Optional[FaultSchedule] = None
        if chaos is not None:
            self.chaos = (chaos if isinstance(chaos, FaultSchedule)
                          else FaultSchedule(list(chaos)))
        self.cache_server = None
        overrides = {}
        if cache_server:
            from pinot_tpu.cache.remote import CacheServer
            from pinot_tpu.utils.metrics import get_registry
            self.cache_server = CacheServer(
                metrics=get_registry("cache_server"))
            self.cache_server.start()
            overrides = {
                "pinot.server.segment.cache.backend": "tiered",
                "pinot.server.segment.cache.remote.address":
                    self.cache_server.address,
                "pinot.broker.result.cache.backend": "tiered",
                "pinot.broker.result.cache.remote.address":
                    self.cache_server.address,
            }
        if overrides:
            config = (config or PinotConfiguration()).with_overrides(overrides)
        self.config = config
        self.servers: List[MiniClusterServer] = [
            MiniClusterServer(f"server_{i}", use_tpu=use_tpu, config=config)
            for i in range(num_servers)]
        self.routing = BrokerRoutingManager()
        self._connections: Dict[str, ServerConnection] = {}
        self.broker: Optional[BrokerRequestHandler] = None
        self.brokers: List[BrokerRequestHandler] = []
        self._num_brokers = max(1, int(num_brokers))
        self.http: Optional[BrokerHttpServer] = None
        self._routes: Dict[str, RoutingTable] = {}
        #: opt-in tier-1 broker result cache (cache/broker_cache.py)
        self._result_cache_enabled = result_cache

    # ------------------------------------------------------------------
    def _make_result_cache(self):
        if not self._result_cache_enabled:
            return None
        from pinot_tpu.cache.broker_cache import BrokerResultCache
        from pinot_tpu.utils.metrics import get_registry
        if self.config is not None:
            cfg = self.config.with_overrides(
                {"pinot.broker.result.cache.enabled": True})
            return BrokerResultCache.from_config(
                cfg, metrics=get_registry("broker"))
        return BrokerResultCache(metrics=get_registry("broker"))

    def start(self, with_http: bool = False) -> None:
        if self.chaos is not None:
            self.chaos.arm()
        for s in self.servers:
            s.start()
            self._connections[s.instance_id] = ServerConnection(
                s.transport.host, s.transport.port)
        from pinot_tpu.mse.dispatcher import QueryDispatcher
        self.mse = QueryDispatcher(
            workers={s.instance_id: s.mse_worker for s in self.servers},
            catalog_fn=self._catalog,
            table_workers_fn=self._table_workers)
        # N broker replicas over the SAME routing view and server
        # connections — each with its own (L1) result cache, sharing L2
        # through the cache server when one is running
        self.brokers = [
            BrokerRequestHandler(self.routing, self._connections,
                                 mse_dispatcher=self.mse,
                                 result_cache=self._make_result_cache(),
                                 config=self.config)
            for _ in range(self._num_brokers)]
        self.broker = self.brokers[0]
        if with_http:
            self.http = BrokerHttpServer(self.broker)
            self.http.start()

    def stop(self) -> None:
        if self.http is not None:
            self.http.stop()
        if getattr(self, "mse", None) is not None:
            self.mse.stop()
        for c in self._connections.values():
            c.close()
        for b in self.brokers:
            if b.result_cache is not None:
                b.result_cache.close()
        for s in self.servers:
            s.stop()
        if self.cache_server is not None:
            self.cache_server.stop()
        if self.chaos is not None:
            self.chaos.disarm()

    # -- multi-stage catalog / placement ------------------------------------
    def _catalog(self):
        """Logical table -> column names, unioned over all servers."""
        cat = {}
        for s in self.servers:
            dm = s.data_manager
            for phys in dm.table_names:
                logical = phys
                for suffix in ("_OFFLINE", "_REALTIME"):
                    if phys.endswith(suffix):
                        logical = phys[: -len(suffix)]
                tdm = dm.table(phys, create=False)
                sdms = tdm.acquire_segments(None)
                try:
                    if sdms:
                        cat.setdefault(logical,
                                       list(sdms[0].segment.column_names))
                finally:
                    type(tdm).release_all(sdms)
        return cat

    def _table_workers(self, table: str):
        """Servers hosting at least one segment of the (logical) table."""
        out = []
        wanted = (table, table + "_OFFLINE", table + "_REALTIME")
        for s in self.servers:
            for phys in s.data_manager.table_names:
                if phys in wanted:
                    out.append(s.instance_id)
                    break
        if not out:
            raise ValueError(f"no servers host table {table!r}")
        return out

    # ------------------------------------------------------------------
    def add_table(self, table_name: str, table_type: str = "OFFLINE",
                  time_column: Optional[str] = None,
                  time_boundary: Optional[int] = None) -> None:
        rt = self._routes.get(table_name)
        if rt is None:
            rt = RoutingTable()
            self._routes[table_name] = rt
        route = TableRoute(f"{table_name}_{table_type}", time_column=time_column)
        if table_type == "OFFLINE":
            rt.offline = route
        else:
            rt.realtime = route
        if time_boundary is not None:
            rt.time_boundary = time_boundary
        self.routing.set_route(table_name, rt)

    def add_segment(self, table_name: str, segment: ImmutableSegment,
                    server_idx: int, table_type: str = "OFFLINE",
                    replicas: Sequence[int] = ()) -> None:
        """Load the segment on server_idx (+replicas) and register routing."""
        physical = f"{table_name}_{table_type}"
        targets = [server_idx, *replicas]
        for idx in targets:
            self.servers[idx].data_manager.table(physical).add_segment(segment)
        rt = self._routes[table_name]
        route = rt.offline if table_type == "OFFLINE" else rt.realtime
        meta = segment.metadata
        route.segments[segment.name] = SegmentInfo(
            name=segment.name,
            servers=[self.servers[i].instance_id for i in targets],
            start_time=meta.start_time, end_time=meta.end_time,
            version=meta.crc)

    def remove_segment(self, table_name: str, segment_name: str,
                       table_type: str = "OFFLINE") -> None:
        """Unload from every server and drop from routing (bumps the
        routing epoch, so tier-1 cache entries go unaddressable)."""
        physical = f"{table_name}_{table_type}"
        for s in self.servers:
            tdm = s.data_manager.table(physical, create=False)
            if tdm is not None:
                tdm.remove_segment(segment_name)
        rt = self._routes.get(table_name)
        route = None if rt is None else (
            rt.offline if table_type == "OFFLINE" else rt.realtime)
        if route is not None:
            route.segments.pop(segment_name, None)

    def query(self, sql: str):
        assert self.broker is not None, "cluster not started"
        return self.broker.handle(sql)
