"""Separate-process cluster roles: controller, server, broker, minion.

Reference parity: the role starters — BaseControllerStarter.java:150,
BaseServerStarter.java:135 (start():578 joins Helix as PARTICIPANT,
registers the state-model factory reacting to OFFLINE->ONLINE
transitions), BaseBrokerStarter.java:104 (BrokerRoutingManager watching
ExternalView). Each run_* function below is one OS process's main loop;
tools/admin.py exposes them as start-controller / start-server /
start-broker subcommands, and tests/test_multiprocess_cluster.py starts
real processes through them (ref ClusterTest.java:92's embedded cluster,
promoted to actual process isolation).

State flows through the coordination service (controller/coordination.py):
servers watch for segments assigned to them and load/unload to converge
(the Helix state-transition analog); brokers watch and rebuild routing
tables + server connections (the ExternalView routing rebuild).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Set

from pinot_tpu.controller.coordination import CoordinationClient

log = logging.getLogger(__name__)


def _start_admin(cfg, key: str, roles) -> Optional[object]:
    """Per-role /metrics + /debug surface (trace_store.DebugHttpServer)
    for roles without an HTTP edge. Knob semantics: 0 = ephemeral port,
    >0 = fixed, <0 = disabled."""
    try:
        port = int(cfg.get(key, 0) or 0)
    except (TypeError, ValueError):
        port = 0
    if port < 0:
        return None
    from pinot_tpu.utils.trace_store import DebugHttpServer
    try:
        srv = DebugHttpServer(roles, port=port)
        srv.start()
    except OSError as e:
        # a debug-only surface must never take the data-serving role
        # down with it (port already owned, bind denied, ...)
        log.warning("admin http (%s=%s) failed to bind: %s — "
                    "continuing without it", key, port, e)
        return None
    return srv


def run_controller(state_dir: str, port: int = 0, host: str = "127.0.0.1",
                   deep_store_uri: Optional[str] = None,
                   http_port: Optional[int] = None, config=None,
                   ready_event: Optional[threading.Event] = None,
                   stop_event: Optional[threading.Event] = None) -> None:
    from pinot_tpu.controller.cluster_state import ClusterState
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.controller.coordination import CoordinationServer
    from pinot_tpu.controller.maintenance import run_retention
    from pinot_tpu.controller.rebalancer import (Rebalancer,
                                                 make_staged_load_fn)
    from pinot_tpu.controller.repair import (RepairChecker,
                                             update_replication_gauges)
    from pinot_tpu.controller.task_manager import TaskManager
    from pinot_tpu.utils.config import PinotConfiguration

    cfg = config or PinotConfiguration()
    if not port:
        port = cfg.get_int("pinot.controller.port")
    state = ClusterState(persist_dir=state_dir)
    # the minion task fabric: durable (journaled) queue + generator
    # cadence + lease-expiry sweeps, served over the coordination channel
    tasks = TaskManager(
        state, config=cfg,
        journal_path=os.path.join(state_dir, "tasks.journal"))
    server = CoordinationServer(state, host=host, port=port,
                                deep_store_uri=deep_store_uri
                                or cfg.get_str(
                                    "pinot.controller.deep.store.uri")
                                or None,
                                task_manager=tasks)
    server.LIVENESS_TTL_S = cfg.get_float(
        "pinot.coordination.liveness.ttl.seconds")
    server.start()
    tasks.start()
    # self-healing plane: journaled move engine + automatic repair.
    # Watch-driven wiring — load_fn STAGES the replica (servers
    # reconcile+warm staged segments, brokers keep routing by
    # `instances`) and waits for the server's load ack; the source
    # drains via the servers' own reconcile once commit_moves drops it
    # from the assignment, so unload_fn is a no-op here.
    rebalancer = Rebalancer(
        state,
        load_fn=make_staged_load_fn(state, server.segment_is_loaded),
        unload_fn=lambda _inst, _table, _name: None,
        live_fn=lambda iid: server.heartbeat_ages().get(
            iid, 0.0) <= server.LIVENESS_TTL_S,
        config=cfg,
        journal_path=os.path.join(state_dir, "rebalance.journal"))
    repair = RepairChecker(state, rebalancer, server.heartbeat_ages,
                           config=cfg)
    controller_api = Controller(state=state, config=cfg)
    controller_api.rebalancer = rebalancer  # share the journaled engine
    # a restart resumes half-finished move plans from the journal —
    # async: staged loads block on server acks, which need the fleet up
    threading.Thread(target=rebalancer.resume, daemon=True,
                     name="rebalance-resume").start()
    # fleet health plane: the controller samples its OWN registry like
    # every role, and sweeps the fleet (the periodic-health-task analog)
    from pinot_tpu.health.history import start_sampling, stop_sampling
    from pinot_tpu.health.rollup import make_cluster_monitor
    start_sampling("controller", cfg)
    monitor = None
    if cfg.get_bool("pinot.cluster.health.enabled", True):
        monitor = make_cluster_monitor(state, server, config=cfg)
        monitor.start()
    rest = None
    if http_port is not None:
        from pinot_tpu.controller.http_api import ControllerHttpServer
        rest = ControllerHttpServer(state, coordination=server,
                                    host=host, port=http_port,
                                    task_manager=tasks,
                                    health_monitor=monitor,
                                    controller=controller_api)
        rest.start()
        print(f"controller REST on {rest.host}:{rest.port}", flush=True)
    print(f"controller listening on {server.address}", flush=True)
    if ready_event is not None:
        ready_event.set()
    stop = stop_event or threading.Event()
    retention_every = cfg.get_float(
        "pinot.controller.retention.frequency.seconds")
    repair_every = cfg.get_float(
        "pinot.controller.repair.frequency.seconds")
    last_maintenance = time.time()
    last_repair = time.time()
    try:
        while not stop.wait(1.0):
            if time.time() - last_maintenance > retention_every:
                last_maintenance = time.time()
                try:
                    # removals notify watchers: servers reconcile the
                    # expired segments away, brokers rebuild routes (the
                    # routing epoch moves, so cached results for the
                    # dropped segments become unaddressable)
                    run_retention(state)
                except Exception:  # noqa: BLE001 — periodic must survive
                    log.exception("retention pass failed")
            if repair_every > 0 \
                    and time.time() - last_repair > repair_every:
                last_repair = time.time()
                try:
                    # SegmentStatusChecker + RebalanceChecker tick:
                    # refresh the replication gauges, then repair any
                    # debounced-dead instance's segments
                    update_replication_gauges(state)
                    repair.check_once()
                except Exception:  # noqa: BLE001 — periodic must survive
                    log.exception("repair pass failed")
    finally:
        if rest is not None:
            rest.stop()
        if monitor is not None:
            monitor.stop()
        stop_sampling("controller")
        tasks.stop()
        rebalancer.close()
        server.stop()


def run_cache_server(port: int = 0, host: str = "127.0.0.1", config=None,
                     ready_event: Optional[threading.Event] = None,
                     stop_event: Optional[threading.Event] = None) -> None:
    """The cache-server role: one shared LruTtlCache byte budget serving
    GET/SET/DELETE/STATS over TCP (cache/remote.py) — the L2 every
    broker's result cache and server's segment cache mounts when its
    backend knob says `tiered`. Stateless across restarts by design:
    entries are recomputable, so durability would buy nothing."""
    from pinot_tpu.cache.remote import CacheServer
    from pinot_tpu.utils.config import PinotConfiguration
    from pinot_tpu.utils.metrics import get_registry

    cfg = config or PinotConfiguration()
    if not port:
        port = cfg.get_int("pinot.cache.server.port")
    server = CacheServer(
        host=host, port=port,
        max_bytes=cfg.get_int("pinot.cache.server.bytes"),
        ttl_seconds=cfg.get_float("pinot.cache.server.ttl.seconds"),
        metrics=get_registry("cache_server"))
    server.start()
    admin = _start_admin(cfg, "pinot.cache.server.admin.port",
                         ["cache_server"])
    from pinot_tpu.health.history import start_sampling, stop_sampling
    start_sampling("cache_server", cfg)
    if admin is not None:
        print(f"cache server admin http on {admin.host}:{admin.port}",
              flush=True)
    print(f"cache server listening on {server.address}", flush=True)
    if ready_event is not None:
        ready_event.set()
    stop = stop_event or threading.Event()
    try:
        while not stop.wait(2.0):
            pass
    finally:
        stop_sampling("cache_server")
        if admin is not None:
            admin.stop()
        server.stop()


def run_minion(instance_id: str, coordinator: str,
               task_types=None, work_dir=None, config=None,
               ready_event: Optional[threading.Event] = None,
               stop_event: Optional[threading.Event] = None) -> None:
    """The minion role: one background-task worker process leasing work
    from the controller's task queue (minion/worker.py). Modeled on
    run_cache_server — stateless across restarts: in-flight work is
    protected by the lease protocol (an unfinished task's lease expires
    and requeues), and committed work lives in the deep store + cluster
    state, so a killed minion loses nothing."""
    from pinot_tpu.minion.worker import MinionWorker
    from pinot_tpu.utils.config import PinotConfiguration

    cfg = config or PinotConfiguration()
    worker = MinionWorker(instance_id, coordinator, work_dir=work_dir,
                          task_types=task_types, config=cfg)
    worker.start()
    admin = _start_admin(cfg, "pinot.minion.admin.port", ["minion"])
    from pinot_tpu.health.history import start_sampling, stop_sampling
    start_sampling("minion", cfg)
    if admin is not None:
        print(f"minion admin http on {admin.host}:{admin.port}",
              flush=True)
        # re-register with the scrape URL so the controller's
        # cluster-health sweep reads this worker's /debug/health
        try:
            worker.client.register_instance(
                instance_id, "127.0.0.1", 0, tags=["minion"],
                admin_url=f"http://{admin.host}:{admin.port}")
        except (ConnectionError, OSError, RuntimeError):
            pass
    print(f"minion {instance_id} polling {coordinator}", flush=True)
    if ready_event is not None:
        ready_event.set()
    stop = stop_event or threading.Event()
    try:
        while not stop.wait(2.0):
            try:
                worker.client.request("heartbeat", instance_id=instance_id)
            except (ConnectionError, OSError, RuntimeError):
                pass
    finally:
        stop_sampling("minion")
        if admin is not None:
            admin.stop()
        worker.stop()


class ServerRole:
    """One server process: query transport + data manager + state watch."""

    def __init__(self, instance_id: str, coordinator: str,
                 query_port: int = 0, host: str = "127.0.0.1",
                 use_tpu: bool = False,
                 download_dir: Optional[str] = None,
                 config=None, tenant: Optional[str] = None):
        import tempfile

        from pinot_tpu.server.data_manager import InstanceDataManager
        from pinot_tpu.server.query_server import (
            QueryServer, ServerQueryExecutor)
        from pinot_tpu.utils.config import PinotConfiguration

        cfg = config or PinotConfiguration()
        self.config = cfg
        self.instance_id = instance_id
        self.client = CoordinationClient(coordinator)
        self.data_manager = InstanceDataManager(instance_id)
        self.executor = ServerQueryExecutor(self.data_manager,
                                            use_tpu=use_tpu, config=cfg)
        self.transport = QueryServer(
            self.executor, host=host,
            port=query_port or cfg.get_int("pinot.server.query.port"),
            num_threads=cfg.get_int("pinot.server.query.num.threads"),
            scheduler=cfg.get_str("pinot.server.query.scheduler"))
        #: local cache for deep-store segment downloads — deterministic
        #: per instance so restarts REUSE extracted copies instead of
        #: leaking a fresh tempdir per process lifetime
        self.download_dir = download_dir or os.path.join(
            tempfile.gettempdir(), f"pinot-tpu-dl-{instance_id}")
        self._loaded: Set[tuple] = set()  # (physical_table, segment_name)
        #: (physical_table, partition_id) -> RealtimeSegmentDataManager
        self._rt_managers: Dict[tuple, object] = {}
        #: per-TABLE ingestion lag trackers, metrics-wired: gauges
        #: `ingestion_delay_ms{table=,partition=}` feed dashboards, and
        #: the backpressure controller reads them for the lag ceiling.
        #: Per table, not per server — partition ids collide across
        #: tables, and one table's consumer stopping must not zero
        #: another's lag
        self._delay_trackers: Dict[str, object] = {}
        #: physical_table -> (partition ids, discovered-at) — cached so a
        #: watch storm doesn't re-dial the stream broker per notification,
        #: refreshed periodically so added partitions start consuming
        #: (ref KafkaStreamMetadataProvider.fetchPartitionCount re-polls)
        self._rt_partitions: Dict[str, tuple] = {}
        self._stopping = False
        #: tenant pool this server joins (tenant:<name> instance tag);
        #: None = the DefaultTenant pool
        self.tenant = tenant
        self._reconcile_lock = threading.Lock()
        #: per-role ops surface: /metrics + /debug/traces + /debug/queries
        self.admin_http = None
        # admission memory shedding reuses the ingest accounting: the
        # worst partition's non-durable bytes against the per-consumer
        # budget (0 budget = never sheds on ingest memory)
        self.executor.add_memory_pressure_source(self._ingest_pressure)

    def _ingest_pressure(self) -> float:
        """Worst per-partition ingest-memory fraction (mutable + sealed
        pending-build bytes vs pinot.server.ingest.memory.bytes)."""
        budget = self.config.get_int("pinot.server.ingest.memory.bytes")
        if budget <= 0:
            return 0.0
        # lint: unlocked(point-in-time snapshot; dict ops are atomic under the GIL and a racing reconcile add only delays one pressure read)
        managers = list(self._rt_managers.values())
        worst = 0.0
        for mgr in managers:
            try:
                worst = max(worst, mgr.ingest_bytes() / budget)
            except Exception:  # noqa: BLE001 — a dying consumer must
                pass           # not take admission down
        return worst

    #: partition-discovery refresh interval
    RT_PARTITION_TTL_S = 30.0

    def start(self) -> None:
        self.transport.start()
        self.admin_http = _start_admin(
            self.config, "pinot.server.admin.port", ["server"])
        if self.admin_http is not None:
            log.info("server %s admin http on %s:%s", self.instance_id,
                     self.admin_http.host, self.admin_http.port)
        # fleet health plane: the background registry sampler (metrics
        # history + SLO watchdog hook) for this process's server role
        from pinot_tpu.health.history import start_sampling
        start_sampling("server", self.config)
        self.client.register_instance(
            self.instance_id, self.transport.host, self.transport.port,
            tags=[f"tenant:{self.tenant}"] if self.tenant else None,
            admin_url=(f"http://{self.admin_http.host}:"
                       f"{self.admin_http.port}"
                       if self.admin_http is not None else ""))
        self.reconcile()
        self.client.watch(lambda _v: self.reconcile())

    def stop(self) -> None:
        from pinot_tpu.health.history import stop_sampling
        stop_sampling("server")
        if self.admin_http is not None:
            self.admin_http.stop()
            self.admin_http = None
        with self._reconcile_lock:  # no reconcile mid-shutdown
            self._stopping = True
            managers = list(self._rt_managers.values())
        # graceful drain, two-phase so shutdown does not scale with the
        # partition count: request every seal FIRST (the force flags make
        # each consumer thread seal concurrently, builds overlapping on
        # their own pools), then drain+join each — the per-manager waits
        # mostly find the work already done
        for mgr in managers:
            try:
                mgr.force_commit(wait_s=0.0)
            except Exception:  # noqa: BLE001 — drain is best-effort
                pass
        for mgr in managers:
            # force-commit the non-empty mutable (through the completion
            # FSM) and persist the final checkpoint, so a rolling restart
            # loses zero rows
            mgr.stop(timeout=5.0, drain=True)
        self.client.close()
        self.transport.stop()
        self.data_manager.shutdown()
        self.executor.fingerprint_log.close()

    # ------------------------------------------------------------------
    def reconcile(self) -> None:
        """Converge local segments to the coordinator's assignment (the
        OFFLINE->ONLINE / ONLINE->OFFLINE transition handler,
        ref SegmentOnlineOfflineStateModelFactory.java:44)."""
        from pinot_tpu.segment.loader import load_segment
        with self._reconcile_lock:
            if self._stopping:
                return
            try:
                blob = self.client.get_state()
            except (ConnectionError, OSError, RuntimeError):
                log.warning("coordinator unreachable; keeping local state")
                return
            # tenant scheduling weights ride the table configs: push
            # them into the query scheduler so weighted-fair groups are
            # shaped before the tenant's first query arrives
            sched = self.transport.scheduler
            if hasattr(sched, "set_tenant_weight"):
                for cfg_d in blob.get("tables", {}).values():
                    tn = cfg_d.get("tenants") or {}
                    if tn.get("server"):
                        sched.set_tenant_weight(
                            tn["server"], float(tn.get("weight", 1.0)))
            wanted: Set[tuple] = set()
            acks: List[tuple] = []
            for table, segs in blob.get("segments", {}).items():
                for name, st in segs.items():
                    # a STAGED replica (rebalance load-before-route)
                    # loads+warms exactly like an assigned one — brokers
                    # just don't route to it until the move commits
                    staged = self.instance_id in st.get("staged", ())
                    if (self.instance_id in st.get("instances", ())
                            or staged) \
                            and st.get("status") == "ONLINE" \
                            and st.get("dir_path"):
                        wanted.add((table, name))
                        if (table, name) not in self._loaded:
                            tdm = self.data_manager.table(
                                table, create=False)
                            if tdm is not None \
                                    and name in tdm.segment_names:
                                # already serving a local copy (realtime
                                # commit on this server) — leave it to its
                                # owner, don't re-download or track it
                                if staged:
                                    acks.append((table, name))
                                continue
                            try:
                                seg = load_segment(
                                    self._localize(table, st["dir_path"]))
                                self.data_manager.table(table) \
                                    .add_segment(seg)
                                self._loaded.add((table, name))
                                if staged:
                                    acks.append((table, name))
                                log.info("loaded %s/%s", table, name)
                            except Exception:  # noqa: BLE001
                                log.exception("failed to load %s/%s",
                                              table, name)
                        elif staged:
                            # already loaded: re-ack — the controller's
                            # ack book may be fresh after a restart
                            acks.append((table, name))
            for table, name in list(self._loaded - wanted):
                tdm = self.data_manager.table(table, create=False)
                if tdm is not None:
                    tdm.remove_segment(name)
                self._loaded.discard((table, name))
                log.info("unloaded %s/%s", table, name)
            for table, name in acks:
                try:
                    # load ack: the rebalancer's staged-load barrier —
                    # routing only flips once the target reports servable
                    self.client.segment_loaded(table, name,
                                               self.instance_id)
                except Exception:  # noqa: BLE001 — ack is best-effort;
                    pass           # the load barrier times out and retries
            self._ensure_realtime(blob)

    def _ensure_realtime(self, blob: dict) -> None:
        """Start one consumer per (REALTIME table, stream partition) —
        every registered server consumes every partition, the completion
        FSM on the controller elects exactly one committer per segment
        (ref RealtimeTableDataManager + the CONSUMING state transition)."""
        from pinot_tpu.controller.coordination import RemoteCompletionManager
        from pinot_tpu.ingest.realtime_manager import \
            RealtimeSegmentDataManager
        from pinot_tpu.ingest.stream import StreamConfig, get_stream_factory
        from pinot_tpu.models import Schema, TableConfig
        import pinot_tpu.ingest.tcp_stream  # noqa: F401 — registers 'tcp'

        for logical, cfg_d in blob.get("tables", {}).items():
            cfg = TableConfig.from_dict(cfg_d)
            sic = cfg.ingestion.stream
            if cfg.table_type.value != "REALTIME" or sic is None:
                continue
            schema_d = blob.get("schemas", {}).get(logical)
            if schema_d is None:
                continue
            schema = Schema.from_dict(schema_d)
            props = dict(sic.properties)
            stream_cfg = StreamConfig(
                stream_type=sic.stream_type, topic=sic.topic,
                properties=props,
                flush_threshold_rows=int(
                    props.get("flushThresholdRows", 100_000)),
                flush_threshold_time_ms=int(
                    props.get("flushThresholdTimeMs", 6 * 3600 * 1000)))
            physical = cfg.table_name_with_type
            cached = self._rt_partitions.get(physical)
            if cached is not None and \
                    time.time() - cached[1] < self.RT_PARTITION_TTL_S:
                partitions = cached[0]
            else:
                # (re)discover: cheap enough per TTL, and added topic
                # partitions start consuming without a server restart
                try:
                    meta = get_stream_factory(stream_cfg) \
                        .create_metadata_provider(stream_cfg)
                    partitions = meta.partition_ids()
                    close = getattr(meta, "close", None)
                    if close is not None:
                        close()
                except Exception:  # noqa: BLE001 — stream not up yet
                    if cached is None:
                        log.warning("stream metadata unavailable for %s",
                                    physical)
                        continue
                    partitions = cached[0]
                self._rt_partitions[physical] = (partitions, time.time())
            store = None
            if blob.get("deep_store_uri"):
                from pinot_tpu.segment.fs import SegmentDeepStore
                store = SegmentDeepStore(blob["deep_store_uri"])
            for pid in partitions:
                key = (physical, pid)
                if key in self._rt_managers:
                    continue
                tdm = self.data_manager.table(physical)
                seg_store = os.path.join(self.download_dir, "rt", physical)
                # resume AFTER this partition's committed segments: the
                # persisted end_offset/seq are the replay checkpoint (ref
                # StreamPartitionMsgOffset in segment ZK metadata)
                start_offset, start_seq = self._rt_checkpoint(
                    blob, physical, pid)
                holder: Dict[str, object] = {}
                from pinot_tpu.utils.metrics import get_registry
                mgr = RealtimeSegmentDataManager(
                    cfg, schema, stream_cfg, pid, tdm, seg_store,
                    start_offset=start_offset,
                    completion_manager=RemoteCompletionManager(self.client),
                    instance_id=self.instance_id,
                    deep_store=store,
                    on_commit=self._rt_committed(physical, pid, holder),
                    on_open=self._rt_opened(physical, pid),
                    start_seq=start_seq,
                    ingestion_delay_tracker=self.delay_tracker_for(
                        physical),
                    config=self.config, metrics=get_registry("server"),
                    recover_segments=self._rt_recover_segments(
                        blob, physical, pid))
                holder["mgr"] = mgr
                mgr.start()
                self._rt_managers[key] = mgr
                log.info("consuming %s partition %d from %s (seq %d)",
                         physical, pid, start_offset, start_seq)

    def delay_tracker_for(self, physical: str):
        """The (lazily created) lag tracker for one realtime table."""
        from pinot_tpu.ingest.realtime_manager import IngestionDelayTracker
        from pinot_tpu.utils.metrics import get_registry
        tracker = self._delay_trackers.get(physical)
        if tracker is None:
            tracker = IngestionDelayTracker(
                metrics=get_registry("server"),
                labels={"instance": self.instance_id, "table": physical})
            self._delay_trackers[physical] = tracker
        return tracker

    def _rt_recover_segments(self, blob: dict, physical: str,
                             pid: int) -> list:
        """Restart recovery for upsert/dedup tables: the partition's
        already-loaded committed segments, in seq order, so the new
        manager re-registers their rows into the metadata map (upsert
        via the persisted validDocIds snapshots) before consuming —
        resumed consumption then neither replays committed rows as fresh
        duplicates nor forgets which rows already lost their upsert
        battle. Append-only tables skip the scan entirely."""
        from pinot_tpu.models.table_config import base_table_name
        cfg_d = blob.get("tables", {}).get(base_table_name(physical), {}) or {}
        if not cfg_d.get("upsertConfig") and not cfg_d.get("dedupConfig"):
            return []
        tdm = self.data_manager.table(physical, create=False)
        if tdm is None:
            return []
        local = set(tdm.segment_names)
        entries = []
        for name, st in blob.get("segments", {}).get(physical, {}).items():
            if st.get("partition_id") != pid or name not in local:
                continue
            parts = name.split("__")
            try:
                seq = int(parts[2]) if len(parts) >= 3 else 0
            except ValueError:
                seq = 0
            entries.append((seq, name))
        out = []
        for _seq, name in sorted(entries):
            seg = tdm.current_segment(name)
            if seg is not None:
                out.append(seg)
        return out

    @staticmethod
    def _rt_checkpoint(blob: dict, physical: str, pid: int):
        """(resume offset, next seq) from the persisted segment states —
        max committed end_offset and max seen sequence + 1."""
        from pinot_tpu.ingest.stream import LongMsgOffset
        best_off = None
        next_seq = 0
        for name, st in blob.get("segments", {}).get(physical, {}).items():
            if st.get("partition_id") != pid:
                continue
            parts = name.split("__")
            if len(parts) >= 3:
                try:
                    next_seq = max(next_seq, int(parts[2]) + 1)
                except ValueError:
                    pass
            off = st.get("end_offset")
            if st.get("status") == "ONLINE" and off is not None:
                off_i = int(str(off))
                if best_off is None or off_i > best_off:
                    best_off = off_i
        return (LongMsgOffset(best_off) if best_off is not None else None,
                next_seq)

    def _rt_opened(self, physical: str, pid: int):
        def cb(segment_name: str):
            self.client.request("add_segment_replica", segment={
                "name": segment_name, "table": physical,
                "instances": [self.instance_id], "dir_path": None,
                "num_docs": 0, "partition_id": pid,
                "status": "CONSUMING"})
        return cb

    def _rt_committed(self, physical: str, pid: int, holder: dict):
        def cb(segment_name: str, offset):
            mgr = holder.get("mgr")
            uri = getattr(mgr, "last_commit_uri", None)
            from pinot_tpu.segment.fs import is_store_uri
            self.client.request("add_segment_replica", segment={
                "name": segment_name, "table": physical,
                "instances": [self.instance_id],
                # only durable (store) locations are worth persisting —
                # a local build dir dies with this server
                "dir_path": uri if uri and is_store_uri(uri) else None,
                "num_docs": getattr(mgr, "last_commit_docs", 0),
                "partition_id": pid,
                "end_offset": str(offset), "status": "ONLINE"})
        return cb

    def _localize(self, table: str, dir_path: str) -> str:
        """A deep-store URI downloads through PinotFS into the local cache
        (ref BaseTableDataManager.downloadSegmentFromDeepStore); a plain
        path loads in place."""
        from pinot_tpu.segment.fs import localize_segment
        return localize_segment(
            dir_path, os.path.join(self.download_dir, table))


def run_server(instance_id: str, coordinator: str, query_port: int = 0,
               use_tpu: bool = False, config=None,
               ready_event: Optional[threading.Event] = None,
               stop_event: Optional[threading.Event] = None,
               tenant: Optional[str] = None) -> None:
    role = ServerRole(instance_id, coordinator, query_port=query_port,
                      use_tpu=use_tpu, config=config, tenant=tenant)
    role.start()
    print(f"server {instance_id} listening on "
          f"{role.transport.host}:{role.transport.port}", flush=True)
    if ready_event is not None:
        ready_event.set()
    stop = stop_event or threading.Event()
    try:
        while not stop.wait(2.0):
            try:
                # the instance-sweep payload: per-table HBM-resident
                # bytes ride every heartbeat so brokers can prefer the
                # replica whose device memory already holds the columns
                role.client.request(
                    "heartbeat", instance_id=instance_id,
                    residency=role.executor.residency_report())
            except (ConnectionError, OSError, RuntimeError):
                pass
    finally:
        role.stop()


class BrokerRole:
    """One broker process: HTTP edge + routing rebuilt from watches."""

    def __init__(self, coordinator: str, http_port: int = 0,
                 host: str = "127.0.0.1", config=None,
                 instance_id: Optional[str] = None):
        from pinot_tpu.broker.adaptive import AdaptiveServerSelector
        from pinot_tpu.broker.http_api import BrokerHttpServer
        from pinot_tpu.broker.quota import QueryQuotaManager
        from pinot_tpu.broker.request_handler import BrokerRequestHandler
        from pinot_tpu.broker.routing import BrokerRoutingManager
        from pinot_tpu.server.query_server import ServerConnection
        from pinot_tpu.utils.config import PinotConfiguration

        cfg = config or PinotConfiguration()
        self._config = cfg
        self.client = CoordinationClient(coordinator)
        self.routing = BrokerRoutingManager(
            selector=AdaptiveServerSelector(
                mode=cfg.get_str("pinot.broker.adaptive.selector")))
        self.connections: Dict[str, ServerConnection] = {}
        self.quotas = QueryQuotaManager()
        self.handler = BrokerRequestHandler(
            self.routing, self.connections,
            max_fanout_threads=cfg.get_int("pinot.broker.fanout.threads"),
            quota_manager=self.quotas, config=cfg)
        self.http = BrokerHttpServer(self.handler, host=host, port=http_port)
        self._host = host
        self.instance_id = instance_id or f"Broker_{host}_{self.http.port}"
        self._rebuild_lock = threading.Lock()

    def start(self) -> None:
        self.rebuild()
        self.client.watch(lambda _v: self.rebuild())
        self.http.start()
        from pinot_tpu.health.history import start_sampling
        start_sampling("broker", self._config)
        # join the scrapeable fleet: the "broker" role tag keeps segment
        # assignment away (cluster_state.NON_SERVER_TAGS); the broker's
        # own HTTP edge serves /debug/health + /debug/metrics/sample
        self.client.register_instance(
            self.instance_id, self._host, 0, tags=["broker"],
            admin_url=f"http://{self._host}:{self.http.port}")

    def stop(self) -> None:
        from pinot_tpu.health.history import stop_sampling
        stop_sampling("broker")
        self.client.close()
        self.http.stop()
        # snapshot under the rebuild lock: the coordinator-watch thread's
        # rebuild() swaps entries into self.connections under this lock,
        # and iterating the live dict here raced it — a watch firing
        # mid-shutdown grew the dict under the loop (RuntimeError: dict
        # changed size during iteration) and leaked the unclosed swapped-
        # in channels (lock-discipline race found by the static analyzer)
        with self._rebuild_lock:
            conns = list(self.connections.values())
        for c in conns:
            c.close()

    # ------------------------------------------------------------------
    def rebuild(self) -> None:
        """Routing rebuild from coordinator state (the ExternalView-change
        handler, ref BrokerRoutingManager.java:100)."""
        from pinot_tpu.broker.routing import (
            RoutingTable, SegmentInfo, TableRoute)
        from pinot_tpu.models import TableConfig
        from pinot_tpu.server.query_server import ServerConnection
        with self._rebuild_lock:
            try:
                blob = self.client.get_state()
            except (ConnectionError, OSError, RuntimeError):
                log.warning("coordinator unreachable; keeping routes")
                return
            group_selector = getattr(self.routing, "group_selector", None)
            for iid, inst in blob.get("instances", {}).items():
                if group_selector is not None:
                    # instance-sweep residency hints -> replica-choice
                    # tiebreak (heartbeat payload, cluster_state)
                    group_selector.update_residency(
                        iid, inst.get("residency") or {})
                if not inst.get("port"):
                    continue
                cur = self.connections.get(iid)
                if cur is not None and (cur.host, cur.port) == \
                        (inst["host"], inst["port"]):
                    continue
                # new instance OR a restarted one on a fresh port: swap in
                # the new channel; the old object is NOT closed here — a
                # query thread may be mid-request on it, and its own
                # ConnectionError path retires it safely
                self.connections[iid] = ServerConnection(
                    inst["host"], inst["port"])
            for logical, cfg_d in blob.get("tables", {}).items():
                cfg = TableConfig.from_dict(cfg_d)
                self.quotas.set_quota(
                    logical, cfg.query.max_queries_per_second)
                tenant = cfg.tenants.server
                self.quotas.set_table_tenant(logical, tenant)
                self.handler.tenants[logical] = tenant
                # per-tenant QPS ceiling: an operator knob, not a table
                # config (one tenant spans many tables). Applied
                # unconditionally so REMOVING the knob lifts the limit
                # on the next reconcile, symmetric with setting it
                tenant_qps = self._config.get(
                    f"pinot.broker.tenant.quota.qps.{tenant}")
                self.quotas.set_tenant_quota(
                    tenant,
                    float(tenant_qps) if tenant_qps is not None else None)
                physical = cfg.table_name_with_type
                route = TableRoute(
                    physical, time_column=cfg.retention.time_column,
                    num_replica_groups=cfg.routing.num_replica_groups)
                pcol = cfg.routing.partition_column
                nparts = 0
                if pcol and cfg.partition_config.get(pcol):
                    nparts = int(cfg.partition_config[pcol]
                                 .get("numPartitions", 0) or 0)
                for name, st in blob.get("segments", {}) \
                                     .get(physical, {}).items():
                    if st.get("status") == "OFFLINE":
                        continue
                    pid = st.get("partition_id")
                    route.segments[name] = SegmentInfo(
                        name=name, servers=list(st.get("instances", ())),
                        partition_id=pid,
                        partition_column=pcol if pid is not None else None,
                        num_partitions=nparts if pid is not None else 0,
                        start_time=st.get("start_time"),
                        end_time=st.get("end_time"),
                        version=st.get("crc", 0) or 0)
                rt = RoutingTable()
                if cfg.table_type.value == "REALTIME":
                    rt.realtime = route
                else:
                    rt.offline = route
                self.routing.set_route(logical, rt)


def run_broker(coordinator: str, http_port: int = 0, config=None,
               ready_event: Optional[threading.Event] = None,
               stop_event: Optional[threading.Event] = None) -> None:
    from pinot_tpu.utils.config import PinotConfiguration
    cfg = config or PinotConfiguration()
    role = BrokerRole(coordinator,
                      http_port=http_port
                      or cfg.get_int("pinot.broker.http.port"),
                      config=cfg)
    role.start()
    print(f"broker http on 127.0.0.1:{role.http.port}", flush=True)
    if ready_event is not None:
        ready_event.set()
    stop = stop_event or threading.Event()
    try:
        while not stop.wait(2.0):
            try:
                # liveness for the health sweep: a broker that stops
                # heartbeating reads "stale" in /cluster/health
                role.client.request("heartbeat",
                                    instance_id=role.instance_id)
            except (ConnectionError, OSError, RuntimeError):
                pass
    finally:
        role.stop()
