"""Data-frame connectors: bulk load/read between frameworks and tables.

Reference parity: pinot-connectors (Spark 2/3 DataSource, Flink sink) —
the ecosystem bridges. Python's dataframe ecosystem is pandas/pyarrow,
so the connector surface here is:

    from pinot_tpu.connectors import pandas_connector as pc
    pc.write_dataframe(df, table_config, schema, out_dir)   # -> segments
    pc.upload_dataframe(df, cfg, schema, client[, store])   # -> cluster
    df = pc.read_sql("SELECT ...", broker="host:port")      # -> DataFrame
"""
from pinot_tpu.connectors import pandas_connector

__all__ = ["pandas_connector"]
