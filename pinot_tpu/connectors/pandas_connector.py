"""pandas connector: DataFrame -> segments -> cluster, and SQL -> DataFrame.

Reference parity: pinot-connectors/pinot-spark-3-connector (write path:
partition the frame, build segments, push to the controller; read path:
query through the broker into the engine's native frame type). pandas is
the Python ecosystem's dataframe, so it plays Spark's role here. Imports
of pandas are deferred — the connector is optional, like the reference's
plugin jars.
"""
from __future__ import annotations

import os
from typing import List, Optional

from pinot_tpu.models import Schema, TableConfig


def write_dataframe(df, table_config: TableConfig, schema: Schema,
                    out_dir: str, rows_per_segment: Optional[int] = None,
                    segment_prefix: Optional[str] = None) -> List[str]:
    """Build segment directories from a DataFrame (the Spark connector's
    write path without the push). Returns the segment dirs."""
    from pinot_tpu.segment.creator import SegmentCreator
    creator = SegmentCreator(table_config, schema)
    n = len(df)
    per = rows_per_segment or max(n, 1)
    prefix = segment_prefix or table_config.name
    out: List[str] = []
    field_names = [f.name for f in schema.fields if not f.virtual]
    ing = table_config.ingestion
    pipeline = None
    if ing is not None and (ing.transform_configs or ing.filter_function):
        # configured ingestion transforms/filters apply here exactly as
        # in run_ingestion_job — the two ingest paths must agree on data
        from pinot_tpu.ingest.transforms import TransformPipeline
        pipeline = TransformPipeline(table_config, schema)
    seg_i = 0
    for start in range(0, n, per):
        part = df.iloc[start:start + per]
        if pipeline is not None:
            from pinot_tpu.ingest.batch import _rows_to_columns
            # pandas encodes missing values as NaN; the pipeline's null
            # handling expects None (as the CSV/JSON readers produce)
            part = part.astype(object).where(part.notna(), None)
            rows = []
            for rec in part.to_dict("records"):
                try:
                    t = pipeline.transform(rec)
                except Exception:  # noqa: BLE001 — poison rows skip+log,
                    # matching the batch/realtime per-record guards
                    import logging
                    logging.getLogger(__name__).exception(
                        "skipping untransformable record")
                    continue
                if t is not None:
                    rows.append(t)
            if not rows:
                continue  # filter dropped the whole chunk: no segment
            cols = _rows_to_columns(rows, schema)
        else:
            cols = {c: part[c].to_numpy() for c in field_names
                    if c in part.columns}
        seg_dir = os.path.join(out_dir, f"{prefix}_{seg_i}")
        creator.build(cols, seg_dir, f"{prefix}_{seg_i}")
        out.append(seg_dir)
        seg_i += 1
    return out


def upload_dataframe(df, table_config: TableConfig, schema: Schema,
                     client, out_dir: str,
                     rows_per_segment: Optional[int] = None,
                     deep_store=None) -> List[dict]:
    """write_dataframe + register every segment with the coordination
    client (ref the connector's controller push); with a deep_store the
    tars upload there and servers fetch via PinotFS."""
    client.add_table(table_config, schema)
    dirs = write_dataframe(df, table_config, schema, out_dir,
                           rows_per_segment)
    out = []
    for d in dirs:
        if deep_store is not None:
            out.append(client.upload_segment_to_store(
                table_config.name, d, deep_store))
        else:
            out.append(client.upload_segment(table_config.name, d))
    return out


def read_sql(sql: str, broker: str, timeout: float = 60.0):
    """Query through the broker into a DataFrame (the read path)."""
    import pandas as pd

    from pinot_tpu.client import connect
    rs = connect(broker, timeout=timeout).execute(sql)
    return pd.DataFrame(rs.rows, columns=rs.columns)


def from_segments(segments, sql: str):
    """Local (embedded) read: run SQL over loaded segments -> DataFrame
    (useful in notebooks without a cluster)."""
    import pandas as pd

    from pinot_tpu.query.executor import QueryExecutor
    resp = QueryExecutor(list(segments), use_tpu=False).execute(sql)
    table = resp.result_table
    return pd.DataFrame(table.rows, columns=table.columns)
