"""Controller role: cluster state, segment assignment, retention,
rebalance, background (minion) tasks.

Reference parity: pinot-controller (SURVEY.md L7) — PinotHelixResourceManager
(table/schema/instance CRUD + IdealState updates), segment assignment
strategies (helix/core/assignment/segment/), TableRebalancer,
RetentionManager, PinotTaskManager/minion task framework — rebuilt without
ZooKeeper/Helix: an in-process (optionally JSON-persisted) ClusterState
with listener callbacks standing in for ExternalView watches (the ZK-free
control plane of SURVEY.md §7.4).
"""
from pinot_tpu.controller.cluster_state import ClusterState, SegmentState
from pinot_tpu.controller.controller import Controller
from pinot_tpu.controller.rebalancer import Rebalancer
from pinot_tpu.controller.repair import RepairChecker

__all__ = ["ClusterState", "SegmentState", "Controller", "Rebalancer",
           "RepairChecker", "TaskManager", "TaskQueue"]


def __getattr__(name):
    # lazy: task_manager pulls in the task executors (segment creator
    # stack); importing the package for ClusterState alone stays light
    if name in ("TaskManager", "TaskQueue"):
        from pinot_tpu.controller import task_manager
        return getattr(task_manager, name)
    raise AttributeError(name)
