"""Segment assignment strategies.

Reference parity: pinot-controller helix/core/assignment/segment/ —
BalancedNumSegmentAssignment (least-loaded instances),
ReplicaGroupSegmentAssignment (replica groups get full copies;
partition-aware placement inside a group). Returns instance lists per
segment; the controller commits them to ClusterState (IdealState update).

Replica-group invariant: the ORDER of a segment's instance list is its
group membership — `instances[g]` is the group-g replica for every
segment of the table, which is how the broker's
ReplicaGroupInstanceSelector addresses one whole group without a
separate group map. Tenant tags (`tenant:<name>` on InstanceState)
restrict every strategy's candidate pool to the table's tenant.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence

from pinot_tpu.controller.cluster_state import ClusterState, SegmentState


class ReplicaGroupConfigError(ValueError):
    """The instance pool cannot realize the configured replica groups.

    Raised instead of silently degenerating: with
    `len(instances) % num_replica_groups != 0` the old floor-division
    split dropped the trailing instances from EVERY group — servers that
    were registered, healthy, and paid for would simply never receive a
    segment, and nobody would know."""


def _pool(state: ClusterState, tenant: Optional[str]) -> List[str]:
    """The replica-group tiling pool: REGISTERED tenant servers, not the
    momentary live set — a server in a heartbeat blip keeps its group
    slot (it reconciles when it returns; the other groups still serve)
    instead of collapsing the group math and failing every upload."""
    return sorted(i.instance_id
                  for i in state.server_instances(tenant=tenant))


def _split_groups(instances: Sequence[str],
                  num_replica_groups: int) -> List[List[str]]:
    """Partition the (sorted) pool into equal replica groups; refuses
    pools the config cannot tile (ReplicaGroupConfigError)."""
    if num_replica_groups < 1:
        raise ReplicaGroupConfigError(
            f"num_replica_groups must be >= 1, got {num_replica_groups}")
    if len(instances) < num_replica_groups:
        raise ReplicaGroupConfigError(
            f"{len(instances)} instances < {num_replica_groups} "
            f"replica groups")
    if len(instances) % num_replica_groups:
        raise ReplicaGroupConfigError(
            f"{len(instances)} instances do not tile into "
            f"{num_replica_groups} replica groups: the trailing "
            f"{len(instances) % num_replica_groups} instance(s) would be "
            f"silently excluded from every group")
    group_size = len(instances) // num_replica_groups
    return [list(instances[g * group_size:(g + 1) * group_size])
            for g in range(num_replica_groups)]


def assign_for_table(state: ClusterState, cfg, physical: str,
                     segment: str,
                     partition_id: Optional[int] = None) -> List[str]:
    """Strategy dispatch from a TableConfig: replica-group placement when
    `routing.num_replica_groups >= 2`, else balanced — always inside the
    table's tenant pool. The single entry point the upload paths share so
    a table's strategy/tenant can't silently diverge between them."""
    tenant = getattr(getattr(cfg, "tenants", None), "server", None)
    nrg = getattr(getattr(cfg, "routing", None), "num_replica_groups", 0)
    if nrg and nrg >= 2:
        return assign_replica_groups(state, physical, segment, nrg,
                                     partition_id=partition_id,
                                     tenant=tenant)
    return assign_balanced(state, physical, segment,
                           replication=cfg.retention.replication,
                           tenant=tenant)


def assign_balanced(state: ClusterState, table: str, segment: str,
                    replication: int = 1,
                    tenant: Optional[str] = None) -> List[str]:
    """Least-loaded placement (ref BalancedNumSegmentAssignment)."""
    instances = [i.instance_id for i in state.live_instances(tenant=tenant)]
    if not instances:
        raise RuntimeError("no live server instances to assign to"
                           + (f" in tenant {tenant!r}" if tenant else ""))
    load: Dict[str, int] = defaultdict(int)
    for seg in state.table_segments(table):
        for inst in seg.instances:
            load[inst] += 1
    ordered = sorted(instances, key=lambda i: (load[i], i))
    return ordered[:min(replication, len(ordered))]


def assign_replica_groups(state: ClusterState, table: str, segment: str,
                          num_replica_groups: int,
                          partition_id: Optional[int] = None,
                          tenant: Optional[str] = None) -> List[str]:
    """Replica-group placement (ref ReplicaGroupSegmentAssignment): servers
    are split into N groups; each group holds a full copy; inside a group
    the segment goes to partition_id % group_size (partition-aware) or the
    least-loaded member. The returned list is GROUP-ORDERED: element g is
    the group-g replica (the broker selector's addressing contract)."""
    groups = _split_groups(_pool(state, tenant), num_replica_groups)
    load: Dict[str, int] = defaultdict(int)
    for seg in state.table_segments(table):
        for inst in seg.instances:
            load[inst] += 1
    out = []
    for group in groups:
        if partition_id is not None:
            out.append(group[partition_id % len(group)])
        else:
            out.append(min(group, key=lambda i: (load[i], i)))
    return out


def target_assignment(state: ClusterState, table: str,
                      replication: int = 1,
                      num_replica_groups: Optional[int] = None,
                      tenant: Optional[str] = None
                      ) -> Dict[str, List[str]]:
    """Full-table target map used by the rebalancer: round-robin spread in
    segment-name order (deterministic), honoring the strategy."""
    segments = sorted(state.table_segments(table), key=lambda s: s.name)
    instances = _pool(state, tenant)
    if not instances:
        return {}
    out: Dict[str, List[str]] = {}
    if num_replica_groups:
        groups = _split_groups(instances, num_replica_groups)
        for idx, seg in enumerate(segments):
            pick = seg.partition_id if seg.partition_id is not None else idx
            out[seg.name] = [g[pick % len(g)] for g in groups]
        return out
    for idx, seg in enumerate(segments):
        out[seg.name] = [instances[(idx + r) % len(instances)]
                        for r in range(min(replication, len(instances)))]
    return out
