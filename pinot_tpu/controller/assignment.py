"""Segment assignment strategies.

Reference parity: pinot-controller helix/core/assignment/segment/ —
BalancedNumSegmentAssignment (least-loaded instances),
ReplicaGroupSegmentAssignment (replica groups get full copies;
partition-aware placement inside a group). Returns instance lists per
segment; the controller commits them to ClusterState (IdealState update).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence

from pinot_tpu.controller.cluster_state import ClusterState, SegmentState


def assign_balanced(state: ClusterState, table: str, segment: str,
                    replication: int = 1) -> List[str]:
    """Least-loaded placement (ref BalancedNumSegmentAssignment)."""
    instances = [i.instance_id for i in state.live_instances()]
    if not instances:
        raise RuntimeError("no live server instances to assign to")
    load: Dict[str, int] = defaultdict(int)
    for seg in state.table_segments(table):
        for inst in seg.instances:
            load[inst] += 1
    ordered = sorted(instances, key=lambda i: (load[i], i))
    return ordered[:min(replication, len(ordered))]


def assign_replica_groups(state: ClusterState, table: str, segment: str,
                          num_replica_groups: int,
                          partition_id: Optional[int] = None) -> List[str]:
    """Replica-group placement (ref ReplicaGroupSegmentAssignment): servers
    are split into N groups; each group holds a full copy; inside a group
    the segment goes to partition_id % group_size (partition-aware) or the
    least-loaded member."""
    instances = sorted(i.instance_id for i in state.live_instances())
    if len(instances) < num_replica_groups:
        raise RuntimeError(
            f"{len(instances)} instances < {num_replica_groups} replica groups")
    group_size = len(instances) // num_replica_groups
    groups = [instances[g * group_size:(g + 1) * group_size]
              for g in range(num_replica_groups)]
    load: Dict[str, int] = defaultdict(int)
    for seg in state.table_segments(table):
        for inst in seg.instances:
            load[inst] += 1
    out = []
    for group in groups:
        if partition_id is not None:
            out.append(group[partition_id % len(group)])
        else:
            out.append(min(group, key=lambda i: (load[i], i)))
    return out


def target_assignment(state: ClusterState, table: str,
                      replication: int = 1,
                      num_replica_groups: Optional[int] = None
                      ) -> Dict[str, List[str]]:
    """Full-table target map used by the rebalancer: round-robin spread in
    segment-name order (deterministic), honoring the strategy."""
    segments = sorted(state.table_segments(table), key=lambda s: s.name)
    instances = sorted(i.instance_id for i in state.live_instances())
    if not instances:
        return {}
    out: Dict[str, List[str]] = {}
    if num_replica_groups:
        group_size = len(instances) // num_replica_groups
        groups = [instances[g * group_size:(g + 1) * group_size]
                  for g in range(num_replica_groups)]
        for idx, seg in enumerate(segments):
            pick = seg.partition_id if seg.partition_id is not None else idx
            out[seg.name] = [g[pick % len(g)] for g in groups]
        return out
    for idx, seg in enumerate(segments):
        out[seg.name] = [instances[(idx + r) % len(instances)]
                        for r in range(min(replication, len(instances)))]
    return out
