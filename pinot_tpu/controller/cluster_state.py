"""Cluster state: the IdealState/ExternalView + property-store analog.

Reference parity: Helix ZNodes managed by PinotHelixResourceManager —
table configs + schemas (property store), instance list, per-table
segment->instances maps (IdealState), and change listeners (the
ExternalView watch mechanism BrokerRoutingManager relies on,
SURVEY.md L7). Persistence is a JSON directory instead of ZK; listeners
are in-process callbacks.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from pinot_tpu.models import Schema, TableConfig


@dataclass
class SegmentState:
    """One segment's ZK-metadata analog."""
    name: str
    table: str                      # physical table name (with type)
    instances: List[str] = field(default_factory=list)
    dir_path: Optional[str] = None  # deep-store location (local FS for now)
    num_docs: int = 0
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    partition_id: Optional[int] = None
    #: realtime replay checkpoint (ref StreamPartitionMsgOffset in ZK meta)
    end_offset: Optional[str] = None
    status: str = "ONLINE"          # ONLINE | CONSUMING | OFFLINE
    #: content CRC — feeds the broker routing epoch so replacing a
    #: segment invalidates result-cache entries cluster-wide
    crc: int = 0
    #: replicas loading+warming ahead of a rebalance commit: servers
    #: reconcile (load) staged segments, brokers route by ``instances``
    #: only — the rebalancer's load-before-route half-state
    staged: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_dict(cls, d: dict) -> "SegmentState":
        return cls(**d)


#: the untagged server pool every table belongs to unless configured
#: otherwise (ref Helix's DefaultTenant broker/server tag)
DEFAULT_TENANT = "DefaultTenant"

#: instance tag prefix that assigns a server to a tenant pool
TENANT_TAG_PREFIX = "tenant:"

#: role tags whose instances never receive segment assignments (ref
#: Helix instance tags gating assignment): minion workers and — since
#: the cluster-health sweep made every role register for scraping —
#: brokers and cache servers too
NON_SERVER_TAGS = {"minion", "broker", "cache_server"}


@dataclass
class InstanceState:
    instance_id: str
    host: str = "127.0.0.1"
    port: int = 0
    enabled: bool = True
    tags: List[str] = field(default_factory=list)
    #: physical table -> HBM-resident bytes this server advertises
    #: (heartbeat payload; feeds residency-aware broker replica choice)
    residency: Dict[str, int] = field(default_factory=dict)
    #: the instance's /metrics + /debug HTTP surface, scraped by the
    #: controller's cluster-health sweep ("" = not scrapeable)
    admin_url: str = ""

    @property
    def tenant(self) -> str:
        """The tenant pool this instance serves (first `tenant:<name>`
        tag; untagged servers form the DefaultTenant pool)."""
        for t in self.tags:
            if t.startswith(TENANT_TAG_PREFIX):
                return t[len(TENANT_TAG_PREFIX):]
        return DEFAULT_TENANT


class ClusterState:
    def __init__(self, persist_dir: Optional[str] = None):
        self._lock = threading.RLock()
        self.tables: Dict[str, TableConfig] = {}        # logical name -> cfg
        self.schemas: Dict[str, Schema] = {}
        self.instances: Dict[str, InstanceState] = {}
        #: physical table -> {segment name -> SegmentState}
        self.segments: Dict[str, Dict[str, SegmentState]] = {}
        self._listeners: List[Callable[[str], None]] = []
        self.persist_dir = persist_dir
        if persist_dir:
            os.makedirs(persist_dir, exist_ok=True)
            self._load()

    # -- listeners (ExternalView watch analog) ------------------------------
    def add_listener(self, fn: Callable[[str], None]) -> None:
        """fn(physical_table) fires after any assignment change."""
        self._listeners.append(fn)

    def _notify(self, physical_table: str) -> None:
        for fn in list(self._listeners):
            fn(physical_table)

    # -- CRUD ----------------------------------------------------------------
    def add_table(self, config: TableConfig, schema: Schema) -> None:
        with self._lock:
            self.tables[config.name] = config
            self.schemas[schema.name] = schema
            self.segments.setdefault(config.table_name_with_type, {})
        self._persist()

    def drop_table(self, name: str) -> None:
        with self._lock:
            cfg = self.tables.pop(name, None)
            if cfg is not None:
                self.segments.pop(cfg.table_name_with_type, None)
        self._persist()

    def register_instance(self, inst: InstanceState) -> None:
        with self._lock:
            self.instances[inst.instance_id] = inst
        self._persist()

    def live_instances(self, tenant: Optional[str] = None
                       ) -> List[InstanceState]:
        """Enabled SERVER instances — role-tagged instances (minion
        workers register with tags=['minion']) never receive segment
        assignments (ref Helix instance tags gating assignment).
        tenant: restrict to one tenant pool (`tenant:<name>` tags;
        untagged servers are the DefaultTenant pool) so a table's
        segments land only on its tenant's servers."""
        with self._lock:
            out = [i for i in self.instances.values()
                   if i.enabled and not NON_SERVER_TAGS & set(i.tags)]
        if tenant is not None:
            out = [i for i in out if i.tenant == tenant]
        return out

    def server_instances(self, tenant: Optional[str] = None
                         ) -> List[InstanceState]:
        """REGISTERED server instances regardless of liveness — the
        replica-group tiling pool. Group math must be a function of the
        provisioned fleet, not the momentary live set: a server missing
        heartbeats (disabled by the liveness sweep) still owns its group
        slot, exactly as a Helix IdealState keeps a dead participant's
        assignments; shrinking the pool instead would hard-fail every
        upload over a transient blip."""
        with self._lock:
            out = [i for i in self.instances.values()
                   if not NON_SERVER_TAGS & set(i.tags)]
        if tenant is not None:
            out = [i for i in out if i.tenant == tenant]
        return out

    def minion_instances(self) -> List[InstanceState]:
        with self._lock:
            return [i for i in self.instances.values()
                    if i.enabled and "minion" in i.tags]

    # -- segments ------------------------------------------------------------
    def upsert_segment(self, state: SegmentState) -> None:
        with self._lock:
            self.segments.setdefault(state.table, {})[state.name] = state
        self._persist()
        self._notify(state.table)

    def remove_segment(self, table: str, name: str) -> Optional[SegmentState]:
        with self._lock:
            st = self.segments.get(table, {}).pop(name, None)
        if st is not None:
            self._persist()
            self._notify(table)
        return st

    def table_segments(self, table: str) -> List[SegmentState]:
        with self._lock:
            return list(self.segments.get(table, {}).values())

    def merge_segment_replica(self, st: SegmentState,
                              prefer_store_uri: bool = True
                              ) -> SegmentState:
        """Merge-register a replica's report of a segment: instances
        UNION (realtime replicas report the same segment independently),
        scalar fields update when provided, CONSUMING->ONLINE promotes,
        and a durable deep-store dir_path is never displaced by a local
        path (ref IdealState instance-map updates)."""
        from pinot_tpu.segment.fs import is_store_uri
        with self._lock:
            cur = self.segments.setdefault(st.table, {}).get(st.name)
            if cur is not None:
                for inst in st.instances:
                    if inst not in cur.instances:
                        cur.instances.append(inst)
                if st.dir_path and not (
                        prefer_store_uri and cur.dir_path
                        and is_store_uri(cur.dir_path)
                        and not is_store_uri(st.dir_path)):
                    cur.dir_path = st.dir_path
                if st.end_offset:
                    cur.end_offset = st.end_offset
                if st.num_docs:
                    cur.num_docs = st.num_docs
                if st.status == "ONLINE" and cur.status != "ONLINE":
                    cur.status = "ONLINE"  # CONSUMING -> ONLINE seal
                st = cur
            self.segments[st.table][st.name] = st
        self._persist()
        self._notify(st.table)
        return st

    def replace_segments(self, adds: List[SegmentState],
                         removes: List) -> None:
        """Atomic segment swap (the minion segment-replace commit): all
        `adds` upserted and all `removes` [(table, name)] dropped under
        ONE lock hold, ONE persist, ONE notification per affected table
        — watchers (brokers rebuilding routes, servers reconciling) see
        the swapped set, never a half-applied one. Removing an absent
        segment is a no-op, so replaying a committed swap (re-leased
        task after a crash mid-commit) converges instead of corrupting."""
        tables = []
        with self._lock:
            for st in adds:
                self.segments.setdefault(st.table, {})[st.name] = st
                if st.table not in tables:
                    tables.append(st.table)
            for table, name in removes:
                self.segments.get(table, {}).pop(name, None)
                if table not in tables:
                    tables.append(table)
        self._persist()
        for table in tables:
            self._notify(table)

    def set_assignment(self, table: str, assignment: Dict[str, List[str]]) -> None:
        """Bulk update segment->instances (rebalance commit)."""
        with self._lock:
            seg_map = self.segments.get(table, {})
            for name, instances in assignment.items():
                if name in seg_map:
                    seg_map[name].instances = list(instances)
        self._persist()
        self._notify(table)

    def stage_replicas(self, table: str,
                       staging: Dict[str, List[str]]) -> None:
        """Mark replicas as loading/warming ahead of a rebalance commit:
        servers reconcile (load+warm) staged segments, but brokers keep
        routing by ``instances`` — no query reaches a staged replica."""
        with self._lock:
            seg_map = self.segments.get(table, {})
            for name, insts in staging.items():
                st = seg_map.get(name)
                if st is not None:
                    st.staged = sorted(set(st.staged) | set(insts))
        self._persist()
        self._notify(table)

    def unstage_replicas(self, table: str,
                         staging: Dict[str, List[str]]) -> None:
        """Roll staged replicas back (cancelled move): servers unload
        them on the next reconcile."""
        with self._lock:
            seg_map = self.segments.get(table, {})
            for name, insts in staging.items():
                st = seg_map.get(name)
                if st is not None:
                    st.staged = [i for i in st.staged if i not in set(insts)]
        self._persist()
        self._notify(table)

    def commit_moves(self, table: str,
                     assignment: Dict[str, List[str]]) -> None:
        """Rebalance batch commit: flip ``instances`` to the target and
        clear staging for those segments under ONE lock hold, ONE
        persist, ONE notification — watchers see one routing-epoch bump
        per batch, and only replicas that already finished load+warm
        become routable."""
        with self._lock:
            seg_map = self.segments.get(table, {})
            for name, instances in assignment.items():
                st = seg_map.get(name)
                if st is not None:
                    st.instances = list(instances)
                    st.staged = [i for i in st.staged
                                 if i not in set(instances)]
        self._persist()
        self._notify(table)

    # -- persistence ---------------------------------------------------------
    def _persist(self) -> None:
        if not self.persist_dir:
            return
        # the write + rename stay UNDER the lock: two concurrent
        # persists (two servers registering at once) shared the one tmp
        # path outside it — the loser's os.replace raised
        # FileNotFoundError after the winner renamed the file away, and
        # a write landing between the winner's open and rename could
        # ship a torn state.json. Serializing also orders the renames,
        # so the newest snapshot is always the one that survives.
        # Persist is control-plane-rare; file IO under the lock is fine.
        with self._lock:
            blob = {
                "tables": {k: v.to_dict() for k, v in self.tables.items()},
                "schemas": {k: v.to_dict() for k, v in self.schemas.items()},
                "segments": {t: {n: s.to_dict() for n, s in m.items()}
                             for t, m in self.segments.items()},
            }
            tmp = os.path.join(self.persist_dir, "state.json.tmp")
            with open(tmp, "w") as f:
                json.dump(blob, f)
            os.replace(tmp, os.path.join(self.persist_dir, "state.json"))

    def _load(self) -> None:
        path = os.path.join(self.persist_dir, "state.json")
        if not os.path.exists(path):
            return
        with open(path) as f:
            blob = json.load(f)
        for k, v in blob.get("tables", {}).items():
            self.tables[k] = TableConfig.from_dict(v)
        for k, v in blob.get("schemas", {}).items():
            self.schemas[k] = Schema.from_dict(v)
        for t, m in blob.get("segments", {}).items():
            self.segments[t] = {n: SegmentState.from_dict(s)
                                for n, s in m.items()}
