"""Segment completion FSM: exactly-one-committer for multi-replica realtime.

Reference parity: pinot-controller
helix/core/realtime/SegmentCompletionManager.java +
BlockingSegmentCompletionFSM.java — every replica consuming a partition
reports segmentConsumed(offset) at its end-criteria; the controller HOLDs
until the replica set reports (or a deadline), elects the replica with the
highest offset as the committer, tells laggards to CATCHUP, and after the
winner's commitEnd tells everyone else to KEEP (offset matches) or
DISCARD-and-download (behind; here the download is the winner's segment
directory — the shared-FS stand-in for deep store / peer download).

States per segment: HOLDING -> COMMITTER_DECIDED -> COMMITTED.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

#: actions returned to servers (ref SegmentCompletionProtocol)
HOLD = "HOLD"
CATCHUP = "CATCHUP"
COMMIT = "COMMIT"
KEEP = "KEEP"
DISCARD = "DISCARD"

#: segment_commit_end statuses (ref SegmentCompletionProtocol COMMIT_SUCCESS)
COMMIT_SUCCESS = "COMMIT_SUCCESS"
COMMIT_FAILED = "COMMIT_FAILED"


@dataclass
class CompletionResponse:
    action: str
    #: CATCHUP/DISCARD: the offset to reach / the committed offset
    offset: Optional[int] = None
    #: DISCARD: where the committed segment can be fetched (peer/deep store)
    download_path: Optional[str] = None


class _SegmentFsm:
    def __init__(self, num_replicas: int, hold_deadline_s: float):
        self.state = "HOLDING"
        self.committed_at = 0.0
        self.num_replicas = num_replicas
        self.deadline = time.time() + hold_deadline_s
        self.offsets: Dict[str, int] = {}      # instance -> reported offset
        self.committer: Optional[str] = None
        self.committed_offset: Optional[int] = None
        self.download_path: Optional[str] = None
        #: replicas that observed the COMMITTED state (for pruning)
        self.acked: set = set()


class SegmentCompletionManager:
    """Controller-side coordinator, one FSM per committing segment."""

    #: a decided committer that hasn't committed within this multiple of
    #: the hold deadline is presumed dead and the segment re-elects
    COMMIT_TIMEOUT_FACTOR = 4.0

    def __init__(self, num_replicas: int = 1, hold_deadline_s: float = 5.0):
        self.num_replicas = num_replicas
        self.hold_deadline_s = hold_deadline_s
        self._fsms: Dict[str, _SegmentFsm] = {}
        self._names: Dict[tuple, str] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def segment_name(self, table: str, partition_id: int, seq: int) -> str:
        """Controller-assigned LLC-style name — IDENTICAL across replicas
        (ref PinotLLCRealtimeSegmentManager creating the CONSUMING segment
        metadata; replicas must agree on the name to correlate reports)."""
        with self._lock:
            key = (table, partition_id, seq)
            name = self._names.get(key)
            if name is None:
                name = f"{table}__{partition_id}__{seq}__{int(time.time())}"
                self._names[key] = name
            return name

    # ------------------------------------------------------------------
    def segment_consumed(self, instance: str, segment: str,
                         offset: int) -> CompletionResponse:
        """A replica reached its end-criteria at `offset`."""
        with self._lock:
            fsm = self._fsms.get(segment)
            if fsm is None:
                fsm = self._fsms[segment] = _SegmentFsm(
                    self.num_replicas, self.hold_deadline_s)
            if fsm.state == "COMMITTED":
                assert fsm.committed_offset is not None
                fsm.acked.add(instance)
                if offset == fsm.committed_offset:
                    return CompletionResponse(KEEP,
                                              offset=fsm.committed_offset)
                # behind OR ahead: discard and adopt the committed copy
                return CompletionResponse(
                    DISCARD, offset=fsm.committed_offset,
                    download_path=fsm.download_path)
            fsm.offsets[instance] = offset

            if fsm.state == "COMMITTER_DECIDED":
                if instance == fsm.committer:
                    return CompletionResponse(COMMIT)
                if time.time() > fsm.deadline:
                    # the committer went silent: presume it dead, drop its
                    # claim (and stale offset) and re-elect below
                    fsm.offsets.pop(fsm.committer, None)
                    fsm.state = "HOLDING"
                    fsm.committer = None
                else:
                    target = fsm.offsets[fsm.committer]  # type: ignore[index]
                    if offset < target:
                        return CompletionResponse(CATCHUP, offset=target)
                    return CompletionResponse(HOLD)

            # HOLDING: wait for the full replica set or the deadline
            if len(fsm.offsets) < fsm.num_replicas \
                    and time.time() < fsm.deadline:
                return CompletionResponse(HOLD)
            # elect: max offset, ties broken by instance id for determinism
            fsm.committer = max(sorted(fsm.offsets),
                                key=lambda i: fsm.offsets[i])
            fsm.state = "COMMITTER_DECIDED"
            fsm.deadline = time.time() \
                + self.hold_deadline_s * self.COMMIT_TIMEOUT_FACTOR
            if instance == fsm.committer:
                return CompletionResponse(COMMIT)
            target = fsm.offsets[fsm.committer]
            if offset < target:
                return CompletionResponse(CATCHUP, offset=target)
            return CompletionResponse(HOLD)

    def segment_commit_end(self, instance: str, segment: str, offset: int,
                           download_path: Optional[str] = None,
                           success: bool = True) -> str:
        """The elected committer finished (or failed) its build+commit.

        Returns COMMIT_SUCCESS only when this instance's commit was
        accepted; a stale (de-elected or late) committer gets
        COMMIT_FAILED and must discard its build and re-enter
        segment_consumed to reconcile via KEEP/DISCARD against the real
        committer's copy (ref SegmentCompletionProtocol response status)."""
        with self._lock:
            fsm = self._fsms.get(segment)
            if fsm is None:
                return COMMIT_FAILED
            if fsm.state == "COMMITTED" or instance != fsm.committer:
                # a stale committer must not reset or overwrite the FSM
                return COMMIT_FAILED
            if not success:
                # failed committer: drop its claim so the next reporter
                # re-elects (ref FSM returning to HOLDING on commit failure)
                fsm.state = "HOLDING"
                fsm.committer = None
                fsm.deadline = time.time() + self.hold_deadline_s
                return COMMIT_FAILED
            fsm.state = "COMMITTED"
            fsm.committed_at = time.time()
            fsm.committed_offset = offset
            fsm.download_path = download_path
            fsm.acked.add(instance)  # the committer has its copy
            self._prune_locked()
            return COMMIT_SUCCESS

    #: retained COMMITTED FSMs (a fresh FSM for an already-committed
    #: segment would re-elect and double-commit, so entries linger for
    #: late reporters and only the oldest settled ones fall off)
    MAX_COMMITTED_RETAINED = 1024

    #: COMMITTED entries older than this are prunable even when a dead
    #: replica never acked (unbounded-growth guard)
    COMMITTED_TTL_S = 3600.0

    def _prune_locked(self) -> None:
        now = time.time()
        committed = [s for s, f in self._fsms.items()
                     if f.state == "COMMITTED"
                     and (len(f.acked) >= f.num_replicas
                          or now - f.committed_at > self.COMMITTED_TTL_S)]
        excess = len(committed) - self.MAX_COMMITTED_RETAINED
        for s in committed[:max(excess, 0)]:
            del self._fsms[s]
        while len(self._names) > 4 * self.MAX_COMMITTED_RETAINED:
            self._names.pop(next(iter(self._names)))

    # ------------------------------------------------------------------
    def state_of(self, segment: str) -> Optional[str]:
        with self._lock:
            fsm = self._fsms.get(segment)
            return fsm.state if fsm else None
