"""Controller facade: table/segment lifecycle + cluster integration.

Reference parity: the PinotHelixResourceManager surface the REST resources
call into (addTable, addNewSegment, deleteSegment...) plus the periodic
task loop (RetentionManager, RebalanceChecker, SegmentStatusChecker).
Integrates with MiniCluster-style deployments by translating ClusterState
changes into server loads + broker routing rebuilds through the listener
(the OFFLINE->ONLINE Helix transition analog, SURVEY.md §3.5).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from pinot_tpu.controller import maintenance, repair as repair_mod
from pinot_tpu.controller.assignment import assign_for_table
from pinot_tpu.controller.cluster_state import (
    ClusterState, InstanceState, SegmentState)
from pinot_tpu.controller.rebalancer import Rebalancer
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.segment.loader import load_segment


class Controller:
    def __init__(self, state: Optional[ClusterState] = None,
                 task_output_dir: Optional[str] = None,
                 config=None, rebalance_journal: Optional[str] = None,
                 heartbeat_ages_fn: Optional[Callable] = None):
        self.state = state or ClusterState()
        self.task_output_dir = task_output_dir or os.path.join(
            os.getcwd(), "controller_tasks")
        #: instance_id -> (load_fn(table, seg_dir), unload_fn(table, name));
        #: the state-transition channel to servers (Helix message analog)
        self._server_hooks: Dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: the journaled move engine behind rebalance + repair
        self.rebalancer = Rebalancer(
            self.state, load_fn=self._hook_load, unload_fn=self._hook_unload,
            config=config, journal_path=rebalance_journal)
        self.repair: Optional[repair_mod.RepairChecker] = None
        if heartbeat_ages_fn is not None:
            self.repair = repair_mod.RepairChecker(
                self.state, self.rebalancer, heartbeat_ages_fn,
                config=config)

    # hook adapters: the move engine speaks (instance, table, SegmentState)
    def _hook_load(self, instance_id: str, table: str,
                   st: Optional[SegmentState]) -> None:
        hooks = self._server_hooks.get(instance_id)
        if hooks is not None and st is not None and st.dir_path:
            hooks[0](table, st.dir_path)

    def _hook_unload(self, instance_id: str, table: str, name: str) -> None:
        hooks = self._server_hooks.get(instance_id)
        if hooks is not None:
            hooks[1](table, name)

    # -- instance / server wiring -------------------------------------------
    def register_server(self, instance_id: str, load_fn: Callable,
                        unload_fn: Callable, host: str = "127.0.0.1",
                        port: int = 0) -> None:
        self.state.register_instance(InstanceState(instance_id, host, port))
        self._server_hooks[instance_id] = (load_fn, unload_fn)

    # -- table / segment API (ref REST resources) ---------------------------
    def add_table(self, config: TableConfig, schema: Schema) -> None:
        self.state.add_table(config, schema)

    def upload_segment(self, logical_table: str, seg_dir: str,
                       table_type: str = "OFFLINE",
                       partition_id: Optional[int] = None) -> SegmentState:
        """Ref controller upload REST -> assign -> notify servers."""
        cfg = self.state.tables[logical_table]
        physical = f"{logical_table}_{table_type}"
        seg = load_segment(seg_dir)
        meta = seg.metadata
        instances = assign_for_table(self.state, cfg, physical,
                                     meta.segment_name,
                                     partition_id=partition_id)
        st = SegmentState(
            name=meta.segment_name, table=physical, instances=instances,
            dir_path=seg_dir, num_docs=meta.num_docs,
            start_time=meta.start_time, end_time=meta.end_time,
            partition_id=partition_id, crc=meta.crc)
        self.state.upsert_segment(st)
        for inst in instances:
            hooks = self._server_hooks.get(inst)
            if hooks is not None:
                hooks[0](physical, seg_dir)  # OFFLINE -> ONLINE
        return st

    def delete_segment(self, physical_table: str, name: str) -> None:
        st = self.state.remove_segment(physical_table, name)
        if st is None:
            return
        for inst in st.instances:
            hooks = self._server_hooks.get(inst)
            if hooks is not None:
                hooks[1](physical_table, name)

    # -- periodic loop (ref ControllerPeriodicTask scheduling) --------------
    def run_maintenance_once(self) -> Dict[str, object]:
        removed = maintenance.run_retention(self.state)
        for st in removed:
            for inst in st.instances:
                hooks = self._server_hooks.get(inst)
                if hooks is not None:
                    hooks[1](st.table, st.name)
        # SegmentStatusChecker leg: per-table replication gauges feed
        # the /debug/health "replication" subsystem + /cluster/health
        status = repair_mod.update_replication_gauges(self.state)
        out: Dict[str, object] = {
            "retentionRemoved": [s.name for s in removed],
            "status": status}
        if self.repair is not None:
            out["repair"] = self.repair.check_once()
        return out

    def start_periodic(self, interval_s: float = 60.0) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.run_maintenance_once()
                except Exception:  # noqa: BLE001 — periodic must survive
                    import logging
                    logging.getLogger(__name__).exception("maintenance failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="controller-periodic")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- rebalance (ref TableRebalancer REST) --------------------------------
    def plan_rebalance(self, logical_table: str,
                       table_type: str = "OFFLINE") -> Dict[str, dict]:
        """Dry-run diff: {segment: {"from": [...], "to": [...]}} for
        segments the target assignment would move. Commits nothing."""
        cfg = self.state.tables[logical_table]
        physical = f"{logical_table}_{table_type}"
        return maintenance.rebalance_table(
            self.state, physical, replication=cfg.retention.replication,
            num_replica_groups=cfg.routing.num_replica_groups or None,
            tenant=cfg.tenants.server, dry_run=True)

    def rebalance(self, logical_table: str, table_type: str = "OFFLINE",
                  dry_run: bool = False) -> Dict[str, dict]:
        """Move the table to its target assignment through the journaled
        move engine: each segment's new replica loads+warms BEFORE the
        assignment commits (no flip-before-load window), sources drain
        after, never below the availability floor."""
        moves = self.plan_rebalance(logical_table, table_type)
        if dry_run or not moves:
            return moves
        physical = f"{logical_table}_{table_type}"
        self.rebalancer.run(physical, moves)
        return moves

    def rebalance_async(self, logical_table: str,
                        table_type: str = "OFFLINE") -> Optional[str]:
        """Async-job variant (REST POST /tables/{t}/rebalance): returns
        a job id to poll via GET /rebalance/{jobId}, or None when the
        table is already at target."""
        moves = self.plan_rebalance(logical_table, table_type)
        if not moves:
            return None
        physical = f"{logical_table}_{table_type}"
        return self.rebalancer.start(physical, moves)

    def rebalance_status(self, job_id: str) -> Optional[dict]:
        return self.rebalancer.status(job_id)

    def rebalance_cancel(self, job_id: str) -> bool:
        return self.rebalancer.cancel(job_id)
