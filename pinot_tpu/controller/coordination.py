"""Coordination service: the ZooKeeper/Helix analog as a TCP watch API.

Reference parity: the reference's entire L7 is EXTERNAL coordination —
Helix IdealState/ExternalView ZNodes watched across processes
(pinot-controller helix/core/PinotHelixResourceManager.java,
pinot-broker routing/BrokerRoutingManager.java:100 re-routing on
ExternalView change) plus the segment-completion REST protocol
(controller/.../realtime/SegmentCompletionManager.java). Here ONE
controller process owns the ClusterState JSON store and the completion
FSM; brokers and servers connect over TCP, mirror the state, and receive
pushed change notifications (the watch).

Wire format: u32 little-endian length | JSON object, both directions.
A connection that sends {"op": "watch"} becomes a long-lived push channel:
the server writes {"event": "change", "version": N} frames on every state
mutation (coalesced by version number — watchers re-pull the full state,
the same read-after-notify pattern as ZK watches).
"""
from __future__ import annotations

import json
import logging
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)

from pinot_tpu.controller.assignment import assign_for_table
from pinot_tpu.controller.cluster_state import (
    ClusterState, InstanceState, SegmentState)
from pinot_tpu.controller.completion import SegmentCompletionManager
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.utils.netframe import (FramedChannel, recv_exact,
                                      recv_frame, send_frame)

# wire helpers shared with the TCP stream connector (utils/netframe.py)
_send_frame = send_frame
_recv_frame = recv_frame
_recv_exact = recv_exact


class CoordinationServer:
    """Controller-side: serves state reads/writes + watches + the
    completion protocol over TCP."""

    def __init__(self, state: ClusterState,
                 completion: Optional[SegmentCompletionManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 deep_store_uri: Optional[str] = None,
                 task_manager=None):
        self.state = state
        self.completion = completion or SegmentCompletionManager()
        #: controller/task_manager.py TaskManager — when present, the
        #: minion task ops (task_lease / task_renew / segment_replace ...)
        #: ride this channel, the Helix Task Framework analog
        self.task_manager = task_manager
        #: cluster-wide deep-store base URI; servers build their
        #: SegmentDeepStore from it (ref controller.data.dir config)
        self.deep_store_uri = deep_store_uri
        self.version = 0
        self._watchers: List[socket.socket] = []
        self._lock = threading.Lock()
        #: serializes watcher pushes — concurrent dispatch threads writing
        #: the same socket would interleave frames and desync the stream
        self._send_lock = threading.Lock()
        self._last_seen: Dict[str, float] = {}
        #: (table, segment) -> instances that acked loading it — the
        #: rebalancer's staged-load barrier (ExternalView-converged
        #: analog): a move only commits routing once the target server
        #: reports the segment servable
        self._loaded_acks: Dict[tuple, set] = {}
        coord = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    while True:
                        req = _recv_frame(sock)
                        if req is None:
                            return
                        if req.get("op") == "watch":
                            coord._add_watcher(sock)
                            # connection is now push-only; park until close
                            while _recv_exact(sock, 1) is not None:
                                pass
                            return
                        try:
                            resp = coord._dispatch(req)
                        except Exception as e:  # noqa: BLE001
                            resp = {"error": f"{type(e).__name__}: {e}"}
                        _send_frame(sock, resp)
                except (ConnectionError, OSError):
                    pass
                finally:
                    coord._drop_watcher(sock)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None
        # state changes from ANY path (completion loops, maintenance)
        # notify watchers
        self.state.add_listener(lambda _table: self._notify())

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="coordination-server")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:  # shutdown() blocks unless serving
            self._server.shutdown()
        self._server.server_close()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------------
    def _add_watcher(self, sock: socket.socket) -> None:
        with self._lock:
            self._watchers.append(sock)
        # initial nudge so a late watcher pulls current state
        try:
            with self._send_lock:
                _send_frame(sock, {"event": "change",
                                   "version": self.version})
        except OSError:
            self._drop_watcher(sock)

    def _drop_watcher(self, sock: socket.socket) -> None:
        with self._lock:
            if sock in self._watchers:
                self._watchers.remove(sock)

    def _notify(self) -> None:
        with self._lock:
            self.version += 1
            watchers = list(self._watchers)
            version = self.version
        for w in watchers:
            try:
                with self._send_lock:
                    _send_frame(w, {"event": "change", "version": version})
            except OSError:
                self._drop_watcher(w)

    # ------------------------------------------------------------------
    def _dispatch(self, req: dict) -> dict:
        op = req["op"]
        if op == "get_state":
            return self._state_blob()
        if op == "add_table":
            cfg = TableConfig.from_dict(req["config"])
            schema = Schema.from_dict(req["schema"])
            self.state.add_table(cfg, schema)
            self._notify()
            return {"ok": True}
        if op == "drop_table":
            self.state.drop_table(req["table"])
            self._notify()
            return {"ok": True}
        if op == "register_instance":
            inst = InstanceState(**req["instance"])
            self.state.register_instance(inst)
            self._last_seen[inst.instance_id] = time.time()
            self._notify()
            return {"ok": True}
        if op == "heartbeat":
            iid = req["instance_id"]
            self._last_seen[iid] = time.time()
            inst = self.state.instances.get(iid)
            if inst is not None:
                # instance-sweep payload: per-table HBM-resident bytes
                # ride the heartbeat so brokers can prefer replicas whose
                # device memory already holds a table's columns
                res = req.get("residency")
                if isinstance(res, dict):
                    inst.residency = {str(k): int(v)
                                      for k, v in res.items()}
                if not inst.enabled:
                    inst.enabled = True  # recovered: rejoin pool
                    self._notify()
            return {"ok": True}
        if op == "segment_loaded":
            # server -> controller ack: a (re)loaded segment is servable
            with self._lock:
                self._loaded_acks.setdefault(
                    (req["table"], req["segment"]), set()).add(
                        req["instance_id"])
            return {"ok": True}
        if op == "upload_segment":
            self._sweep_liveness()
            return self._upload_segment(req)
        if op == "upsert_segment":
            self.state.upsert_segment(SegmentState.from_dict(req["segment"]))
            return {"ok": True}
        if op == "add_segment_replica":
            st = self.state.merge_segment_replica(
                SegmentState.from_dict(req["segment"]))
            return {"segment": st.to_dict()}
        if op == "remove_segment":
            st = self.state.remove_segment(req["table"], req["name"])
            return {"ok": st is not None}
        if op == "segment_name":
            name = self.completion.segment_name(
                req["table"], req["partition_id"], req["seq"])
            return {"name": name}
        if op == "segment_consumed":
            r = self.completion.segment_consumed(
                req["instance"], req["segment"], req["offset"])
            return {"action": r.action, "offset": r.offset,
                    "download_path": r.download_path}
        if op == "segment_commit_end":
            status = self.completion.segment_commit_end(
                req["instance"], req["segment"], req["offset"],
                download_path=req.get("download_path"),
                success=req.get("success", True))
            # a successful commit updates segment metadata in state so
            # brokers route to the sealed copy
            if status == "COMMIT_SUCCESS" and req.get("segment_state"):
                self.state.upsert_segment(
                    SegmentState.from_dict(req["segment_state"]))
            return {"status": status}
        if op.startswith("task_") or op == "segment_replace":
            return self._dispatch_task(op, req)
        raise ValueError(f"unknown op {op!r}")

    def _dispatch_task(self, op: str, req: dict) -> dict:
        """Minion task-fabric ops (ref the Helix Task Framework RPCs +
        the controller task REST resources)."""
        from pinot_tpu.controller.tasks import TaskConfig
        # any worker-attributed RPC proves the worker is alive: a minion
        # blocked inside a long task never reaches its poll-loop
        # heartbeat, but its lease renewals land every few seconds —
        # without this, the liveness sweep disables (and /instances
        # reports stale) exactly the workers doing the most work
        worker = req.get("worker")
        if worker:
            self._last_seen[worker] = time.time()
        tm = self.task_manager
        if tm is None:
            raise ValueError("no task manager on this controller")
        if op == "task_submit":
            t = req["task"]
            e = tm.submit(TaskConfig(
                t["taskType"], t["table"], list(t.get("segments", ())),
                dict(t.get("params", ())), task_id=t.get("taskId", "")))
            return {"task": e.to_dict()}
        if op == "task_lease":
            e = tm.lease(req["worker"], req.get("task_types") or None)
            return {"task": e.to_dict() if e is not None else None}
        if op == "task_renew":
            return tm.queue.renew(req["task_id"], req["worker"],
                                  progress=req.get("progress"))
        if op == "task_complete":
            ok = tm.queue.complete(req["task_id"], req["worker"],
                                   result=req.get("result"))
            return {"ok": ok}
        if op == "task_fail":
            ok = tm.queue.fail(req["task_id"], req["worker"],
                               error=req.get("error", ""),
                               cancelled=req.get("cancelled", False))
            return {"ok": ok}
        if op == "task_cancel":
            state = tm.queue.cancel(req["task_id"])
            return {"ok": state is not None, "state": state}
        if op == "task_get":
            e = tm.queue.get(req["task_id"])
            return {"task": e.to_dict() if e is not None else None}
        if op == "task_list":
            return {"tasks": [e.to_dict()
                              for e in tm.queue.list(req.get("state"))]}
        if op == "segment_replace":
            return tm.segment_replace(
                req.get("task_id", ""), req.get("adds", ()),
                [tuple(r) for r in req.get("removes", ())])
        raise ValueError(f"unknown op {op!r}")

    #: instances silent for this long are disabled (heartbeats come every
    #: ~2s from run_server) so new segments stop landing on corpses
    LIVENESS_TTL_S = 15.0

    def segment_is_loaded(self, table: str, segment: str,
                          instance_id: str) -> bool:
        """Has `instance_id` acked loading (table, segment)? The staged
        load_fn polls this before committing a move's routing."""
        with self._lock:
            return instance_id in self._loaded_acks.get((table, segment),
                                                        ())

    def heartbeat_ages(self) -> Dict[str, float]:
        """Seconds since each instance's last heartbeat/registration —
        the fleet-health sweep the controller REST /instances exposes
        (live/stale tagging for servers AND minion workers)."""
        now = time.time()
        with self._lock:
            return {iid: now - seen
                    for iid, seen in self._last_seen.items()}

    def _sweep_liveness(self) -> None:
        now = time.time()
        changed = False
        for iid, seen in list(self._last_seen.items()):
            inst = self.state.instances.get(iid)
            if inst is not None and inst.enabled \
                    and now - seen > self.LIVENESS_TTL_S:
                inst.enabled = False
                changed = True
                log.warning("instance %s missed heartbeats; disabled", iid)
        if changed:
            self._notify()

    def _upload_segment(self, req: dict) -> dict:
        """Assign + commit a built segment (ref controller upload REST ->
        SegmentAssignment -> IdealState update)."""
        import os

        from pinot_tpu.segment.meta import SegmentMetadata
        logical = req["table"]
        table_type = req.get("table_type", "OFFLINE")
        cfg = self.state.tables[logical]
        physical = f"{logical}_{table_type}"
        if req.get("metadata") is not None:
            # deep-store upload: the client pushed the tar itself and
            # sends metadata + the store URI (ref tar upload REST body)
            meta = SegmentMetadata.from_dict(req["metadata"])
            dir_path = req["dir_path"]
        else:
            with open(os.path.join(req["seg_dir"], "metadata.json")) as f:
                meta = SegmentMetadata.from_dict(json.load(f))
            dir_path = req["seg_dir"]
        instances = assign_for_table(
            self.state, cfg, physical, meta.segment_name,
            partition_id=req.get("partition_id"))
        st = SegmentState(
            name=meta.segment_name, table=physical, instances=instances,
            dir_path=dir_path, num_docs=meta.num_docs,
            start_time=meta.start_time, end_time=meta.end_time,
            partition_id=req.get("partition_id"))
        self.state.upsert_segment(st)
        return {"segment": st.to_dict()}

    def _state_blob(self) -> dict:
        with self.state._lock:
            return {
                "version": self.version,
                "deep_store_uri": self.deep_store_uri,
                "tables": {k: v.to_dict()
                           for k, v in self.state.tables.items()},
                "schemas": {k: v.to_dict()
                            for k, v in self.state.schemas.items()},
                "instances": {k: vars(v).copy()
                              for k, v in self.state.instances.items()},
                "segments": {t: {n: s.to_dict() for n, s in m.items()}
                             for t, m in self.state.segments.items()},
            }


class CoordinationClient:
    """Broker/server-side: request channel + optional watch thread.

    Thread-safe: one socket for requests under a lock; a second socket for
    the watch push channel (the ZK client session analog)."""

    def __init__(self, address: str, timeout: float = 30.0):
        self._ch = FramedChannel(address, timeout=timeout)
        self.host, self.port = self._ch.host, self._ch.port
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def request(self, op: str, **kwargs) -> dict:
        return self._ch.request({"op": op, **kwargs})

    def close(self) -> None:
        self.stop_watch()
        self._ch.close()

    # -- typed helpers --------------------------------------------------
    def get_state(self) -> dict:
        return self.request("get_state")

    def add_table(self, config: TableConfig, schema: Schema) -> None:
        self.request("add_table", config=config.to_dict(),
                     schema=schema.to_dict())

    def register_instance(self, instance_id: str, host: str, port: int,
                          tags: Optional[List[str]] = None,
                          admin_url: str = "") -> None:
        """admin_url: the instance's /metrics + /debug HTTP surface —
        the controller's cluster-health sweep scrapes it (empty = not
        scrapeable; the sweep reports liveness only)."""
        self.request("register_instance", instance={
            "instance_id": instance_id, "host": host, "port": port,
            "enabled": True, "tags": tags or [],
            "admin_url": admin_url})

    def segment_loaded(self, table: str, segment: str,
                       instance_id: str) -> None:
        """Ack that this server loaded (table, segment) and it is
        servable — the rebalancer's load-before-route barrier."""
        self.request("segment_loaded", table=table, segment=segment,
                     instance_id=instance_id)

    def upload_segment(self, table: str, seg_dir: str,
                       table_type: str = "OFFLINE",
                       partition_id: Optional[int] = None) -> dict:
        return self.request("upload_segment", table=table, seg_dir=seg_dir,
                            table_type=table_type, partition_id=partition_id)

    def upload_segment_to_store(self, table: str, seg_dir: str, deep_store,
                                table_type: str = "OFFLINE",
                                partition_id: Optional[int] = None) -> dict:
        """Push the built segment tar to the deep store, then register its
        STORE URI with the controller — servers download through PinotFS,
        so no shared build directory is needed (ref segment upload REST +
        deep-store-backed serving)."""
        import json as _json
        import os as _os

        from pinot_tpu.segment.meta import SegmentMetadata
        with open(_os.path.join(seg_dir, "metadata.json")) as f:
            meta = SegmentMetadata.from_dict(_json.load(f))
        physical = f"{table}_{table_type}"
        uri = deep_store.upload(seg_dir, physical, meta.segment_name)
        return self.request(
            "upload_segment", table=table, table_type=table_type,
            partition_id=partition_id, metadata=meta.to_dict(),
            dir_path=uri)

    # ------------------------------------------------------------------
    def watch(self, callback: Callable[[int], None],
              poll_fallback_s: float = 5.0) -> None:
        """Start the push channel; callback(version) fires on every change
        notification (and periodically as a missed-notification guard)."""

        def loop():
            while not self._watch_stop.is_set():
                sock = None
                try:
                    sock = socket.create_connection(
                        (self.host, self.port), timeout=None)
                    _send_frame(sock, {"op": "watch"})
                    sock.settimeout(poll_fallback_s)
                    while not self._watch_stop.is_set():
                        try:
                            msg = _recv_frame(sock)
                        except socket.timeout:
                            # a timeout can land mid-frame and desync the
                            # stream — reconnect; the server's initial
                            # nudge doubles as the periodic guard pull
                            callback(-1)
                            break
                        if msg is None:
                            break
                        callback(int(msg.get("version", -1)))
                except Exception:  # noqa: BLE001 — the watch must never
                    # die silently (a dead watch means a server that stops
                    # loading assignments); reconnect after a beat
                    log.exception("watch channel error; reconnecting")
                    if self._watch_stop.wait(1.0):
                        return
                finally:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass

        self._watch_thread = threading.Thread(
            target=loop, daemon=True, name="coordination-watch")
        self._watch_thread.start()

    def stop_watch(self) -> None:
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
            self._watch_thread = None


class RemoteCompletionManager:
    """SegmentCompletionManager facade over the coordination client — the
    drop-in `completion_manager` for RealtimeSegmentDataManager in a
    multi-process deployment (ref: servers speak the completion protocol
    to the controller over HTTP; here it rides the coordination channel)."""

    def __init__(self, client: CoordinationClient):
        self.client = client

    def segment_name(self, table: str, partition_id: int, seq: int) -> str:
        return self.client.request("segment_name", table=table,
                                   partition_id=partition_id, seq=seq)["name"]

    def segment_consumed(self, instance: str, segment: str, offset: int):
        from pinot_tpu.controller.completion import CompletionResponse
        r = self.client.request("segment_consumed", instance=instance,
                                segment=segment, offset=offset)
        return CompletionResponse(r["action"], offset=r.get("offset"),
                                  download_path=r.get("download_path"))

    def segment_commit_end(self, instance: str, segment: str, offset: int,
                           download_path: Optional[str] = None,
                           success: bool = True,
                           segment_state: Optional[dict] = None) -> str:
        r = self.client.request(
            "segment_commit_end", instance=instance, segment=segment,
            offset=offset, download_path=download_path, success=success,
            segment_state=segment_state)
        return r["status"]
