"""Controller REST API over the cluster state.

Reference parity: pinot-controller api/resources/ (62 Jersey resources;
the operational core here): table CRUD, schema read, segment listing and
upload registration, instance listing, health — the surface ops tooling
and the React UI call (the UI itself is out of scope; the API it needs
is not).

  GET    /health
  GET    /tables                      -> {"tables": [...]}
  GET    /tables/{name}               -> {"tableConfig": ..., "schema": ...}
  POST   /tables                      <- {"tableConfig": ..., "schema": ...}
  DELETE /tables/{name}
  GET    /tables/{name}/segments      -> per-physical-table segment states
  POST   /tables/{name}/segments      <- {"segDir": path, "tableType": ...}
  GET    /instances                   -> per-instance record + liveness
                                         (lastHeartbeatAgeSeconds,
                                          live|stale|unknown — servers
                                          and minion workers alike)
  GET    /tasks[?state=PENDING]       -> task-fabric queue entries
  GET    /tasks/{id}                  -> one task's lifecycle record
  POST   /tasks                       <- {"taskType", "table", "segments",
                                          "params"} (submit)
  POST   /tasks/{id}/cancel
  POST   /tables/{name}/rebalance     <- {"tableType", "dryRun"} -> async
                                         {"jobId"} (or the dry-run diff)
  GET    /rebalance/{jobId}           -> move-plan progress (byState, done)
  POST   /rebalance/{jobId}/cancel    -> consistent prefix stays applied
"""
from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from pinot_tpu.controller.cluster_state import ClusterState
from pinot_tpu.models import Schema, TableConfig


class ControllerHttpServer:
    def __init__(self, state: ClusterState, coordination=None,
                 host: str = "127.0.0.1", port: int = 0,
                 task_manager=None, health_monitor=None, controller=None):
        self.state = state
        self.coordination = coordination  # CoordinationServer (optional)
        # task fabric (controller/task_manager.py); falls back to the
        # coordination server's manager so both wirings expose /tasks
        self.task_manager = task_manager or getattr(
            coordination, "task_manager", None)
        #: health/rollup.ClusterHealthMonitor behind /cluster/* (optional)
        self.health_monitor = health_monitor
        #: Controller facade (or any object with plan_rebalance /
        #: rebalance_async / rebalance_status / rebalance_cancel) —
        #: backs the async rebalance-job surface
        self.controller = controller
        api = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def do_GET(self):
                try:
                    self._route("GET")
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": str(e)})

            def do_POST(self):
                try:
                    self._route("POST")
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": str(e)})

            def do_DELETE(self):
                try:
                    self._route("DELETE")
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": str(e)})

            def _route(self, method: str):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/")
                if method == "GET" and path == "/health":
                    return self._reply(200, {"status": "OK"})
                if method == "GET" and path == "/metrics":
                    from pinot_tpu.utils.metrics import get_registry
                    body = get_registry(
                        "controller").prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if method == "GET" and path.startswith("/debug/"):
                    from pinot_tpu.utils.trace_store import debug_payload
                    payload = debug_payload("controller", path)
                    if payload is None:
                        return self._reply(404,
                                           {"error": f"no route {path}"})
                    return self._reply(200, payload)
                if method == "GET" and path in ("/cluster/health",
                                                "/cluster/metrics"):
                    mon = api.health_monitor
                    if mon is None:
                        return self._reply(
                            503, {"error": "no cluster health monitor"})
                    return self._reply(
                        200, mon.cluster_health()
                        if path == "/cluster/health"
                        else mon.cluster_metrics())
                if path == "/tasks" or path.startswith("/tasks/"):
                    return self._route_tasks(method, path, query)
                if path.startswith("/rebalance/") or \
                        re.fullmatch(r"/tables/[^/]+/rebalance", path):
                    return self._route_rebalance(method, path)
                if path == "/tables" and method == "GET":
                    with api.state._lock:
                        names = sorted(api.state.tables)
                    return self._reply(200, {"tables": names})
                if path == "/tables" and method == "POST":
                    body = self._body()
                    cfg = TableConfig.from_dict(body["tableConfig"])
                    schema = Schema.from_dict(body["schema"])
                    # through coordination when present: watchers (brokers
                    # /servers) must see the change notification
                    if api.coordination is not None:
                        api.coordination._dispatch({
                            "op": "add_table",
                            "config": cfg.to_dict(),
                            "schema": schema.to_dict()})
                    else:
                        api.state.add_table(cfg, schema)
                    return self._reply(200, {"status": f"added {cfg.name}"})
                if path == "/instances" and method == "GET":
                    with api.state._lock:
                        insts = {k: vars(v).copy() for k, v in
                                 api.state.instances.items()}
                    # fleet-health sweep: every instance that heartbeats
                    # (servers, brokers, minion workers alike) reports
                    # its last-heartbeat age and a live/stale tag; an
                    # instance with no recorded heartbeat (static
                    # wiring, no coordination) reads "unknown"
                    ages = (api.coordination.heartbeat_ages()
                            if api.coordination is not None else {})
                    ttl = (api.coordination.LIVENESS_TTL_S
                           if api.coordination is not None else 15.0)
                    for iid, blob in insts.items():
                        age = ages.get(iid)
                        if age is None:
                            blob["lastHeartbeatAgeSeconds"] = None
                            blob["liveness"] = "unknown"
                        else:
                            blob["lastHeartbeatAgeSeconds"] = round(age, 3)
                            blob["liveness"] = ("live" if age <= ttl
                                                else "stale")
                    return self._reply(200, {"instances": insts})
                m = re.fullmatch(r"/tables/([^/]+)", path)
                if m:
                    name = m.group(1)
                    if method == "GET":
                        cfg = api.state.tables.get(name)
                        if cfg is None:
                            return self._reply(
                                404, {"error": f"no table {name}"})
                        schema = api.state.schemas.get(name)
                        return self._reply(200, {
                            "tableConfig": cfg.to_dict(),
                            "schema": schema.to_dict() if schema else None})
                    if method == "DELETE":
                        if api.coordination is not None:
                            api.coordination._dispatch(
                                {"op": "drop_table", "table": name})
                        else:
                            api.state.drop_table(name)
                        return self._reply(200,
                                           {"status": f"dropped {name}"})
                m = re.fullmatch(r"/tables/([^/]+)/segments", path)
                if m:
                    name = m.group(1)
                    if method == "GET":
                        out = {}
                        with api.state._lock:
                            for suffix in ("_OFFLINE", "_REALTIME"):
                                segs = api.state.segments.get(name + suffix)
                                if segs:
                                    out[name + suffix] = {
                                        n: s.to_dict()
                                        for n, s in segs.items()}
                        return self._reply(200, out)
                    if method == "POST":
                        body = self._body()
                        if api.coordination is None:
                            return self._reply(
                                503, {"error": "no coordination service"})
                        r = api.coordination._dispatch({
                            "op": "upload_segment", "table": name,
                            "seg_dir": body["segDir"],
                            "table_type": body.get("tableType", "OFFLINE")})
                        return self._reply(200, r)
                self._reply(404, {"error": f"no route {method} {path}"})

            def _route_rebalance(self, method: str, path: str):
                """Async rebalance jobs (ref TableRebalancer REST +
                rebalance job ZK metadata): POST starts a journaled move
                plan, GET polls it, cancel keeps the applied prefix."""
                ctl = api.controller
                if ctl is None:
                    return self._reply(503, {"error": "no controller"})
                m = re.fullmatch(r"/tables/([^/]+)/rebalance", path)
                if m and method == "POST":
                    body = self._body()
                    name = m.group(1)
                    if name not in api.state.tables:
                        return self._reply(404,
                                           {"error": f"no table {name}"})
                    ttype = body.get("tableType", "OFFLINE")
                    if body.get("dryRun"):
                        return self._reply(200, {
                            "dryRun": True,
                            "moves": ctl.plan_rebalance(name, ttype)})
                    job_id = ctl.rebalance_async(name, ttype)
                    if job_id is None:
                        return self._reply(200, {"status": "NO_OP",
                                                 "jobId": None})
                    return self._reply(200, {"status": "IN_PROGRESS",
                                             "jobId": job_id})
                m = re.fullmatch(r"/rebalance/([^/]+)", path)
                if m and method == "GET":
                    prog = ctl.rebalance_status(m.group(1))
                    if prog is None:
                        return self._reply(
                            404, {"error": f"no job {m.group(1)}"})
                    return self._reply(200, prog)
                m = re.fullmatch(r"/rebalance/([^/]+)/cancel", path)
                if m and method == "POST":
                    ok = ctl.rebalance_cancel(m.group(1))
                    return self._reply(200, {"cancelled": bool(ok),
                                             "jobId": m.group(1)})
                self._reply(404, {"error": f"no route {method} {path}"})

            def _route_tasks(self, method: str, path: str, query: str):
                """Task-fabric surface (ref PinotTaskRestletResource)."""
                from urllib.parse import parse_qs
                tm = api.task_manager
                if tm is None:
                    return self._reply(503, {"error": "no task manager"})
                if path == "/tasks" and method == "GET":
                    state = (parse_qs(query).get("state") or [None])[0]
                    return self._reply(200, {"tasks": [
                        e.to_dict() for e in tm.queue.list(state)]})
                if path == "/tasks" and method == "POST":
                    from pinot_tpu.controller.tasks import TaskConfig
                    b = self._body()
                    e = tm.submit(TaskConfig(
                        b["taskType"], b["table"],
                        list(b.get("segments", ())),
                        dict(b.get("params", {}))))
                    return self._reply(200, {"task": e.to_dict()})
                m = re.fullmatch(r"/tasks/([^/]+)", path)
                if m and method == "GET":
                    e = tm.queue.get(m.group(1))
                    if e is None:
                        return self._reply(
                            404, {"error": f"no task {m.group(1)}"})
                    return self._reply(200, {"task": e.to_dict()})
                m = re.fullmatch(r"/tasks/([^/]+)/cancel", path)
                if m and method == "POST":
                    state = tm.queue.cancel(m.group(1))
                    if state is None:
                        return self._reply(
                            404, {"error": f"no task {m.group(1)}"})
                    return self._reply(200, {"state": state})
                self._reply(404, {"error": f"no route {method} {path}"})

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"controller-http-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:  # shutdown() blocks unless serving
            self._server.shutdown()
        self._server.server_close()
