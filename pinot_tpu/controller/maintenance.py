"""Periodic maintenance: retention, rebalance, status checks.

Reference parity: pinot-controller periodic task framework —
RetentionManager (retention/RetentionManager.java: drop segments past the
table's retention window by end-time), TableRebalancer
(helix/core/rebalance/TableRebalancer.java: move to a target assignment
with minimal disruption), SegmentStatusChecker (gauges for missing
replicas).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from pinot_tpu.controller.assignment import target_assignment
from pinot_tpu.controller.cluster_state import ClusterState, SegmentState

_UNIT_MS = {
    "MILLISECONDS": 1, "SECONDS": 1000, "MINUTES": 60_000, "HOURS": 3_600_000,
    "DAYS": 86_400_000,
}


def run_retention(state: ClusterState,
                  now_ms: Optional[int] = None) -> List[SegmentState]:
    """Drop segments whose end-time is past retention (ref RetentionManager).
    Returns the removed segment states (caller unloads them from servers)."""
    now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
    removed: List[SegmentState] = []
    for cfg in list(state.tables.values()):
        ret = cfg.retention
        if not ret.retention_time_value or not ret.time_column:
            continue
        window_ms = int(ret.retention_time_value) * _UNIT_MS.get(
            (ret.retention_time_unit or "DAYS").upper(), 86_400_000)
        cutoff = now_ms - window_ms
        table = cfg.table_name_with_type
        for seg in state.table_segments(table):
            if seg.status == "CONSUMING":
                continue
            if seg.end_time is not None and seg.end_time < cutoff:
                state.remove_segment(table, seg.name)
                removed.append(seg)
    return removed


def rebalance_table(state: ClusterState, table: str, replication: int = 1,
                    num_replica_groups: Optional[int] = None,
                    tenant: Optional[str] = None,
                    dry_run: bool = False) -> Dict[str, dict]:
    """Compute (and with dry_run=False, commit) the target-assignment
    diff (ref TableRebalancer's plan step). Returns
    {segment: {'from': [...], 'to': [...]}} for segments that move.
    tenant: restrict the candidate pool to the table's tenant servers.

    NOTE: the non-dry-run path is the STATE-ONLY assignment flip — no
    server loads happen here, so routing can point at replicas that do
    not hold the data yet. Live clusters must go through
    ``rebalancer.Rebalancer`` (Controller.rebalance does), which
    loads+warms targets first and commits per warmed batch; this
    function's dry_run=True diff is its planning input."""
    target = target_assignment(state, table, replication, num_replica_groups,
                               tenant=tenant)
    moves: Dict[str, dict] = {}
    current = {s.name: s.instances for s in state.table_segments(table)}
    for name, to in target.items():
        frm = current.get(name, [])
        if sorted(frm) != sorted(to):
            moves[name] = {"from": frm, "to": to}
    if not dry_run and moves:
        state.set_assignment(table, {n: m["to"] for n, m in moves.items()})
    return moves


def segment_status(state: ClusterState, table: str,
                   expected_replication: int = 1,
                   live: Optional[set] = None) -> Dict[str, int]:
    """Ref SegmentStatusChecker gauges. ``live``: when given (the
    repair checker's view of heartbeat-healthy instances), only
    replicas hosted on live instances count toward replication — a
    dead server's copies are missing even while the assignment still
    names it."""
    segs = state.table_segments(table)
    missing = sum(
        1 for s in segs
        if len([i for i in s.instances if live is None or i in live])
        < expected_replication)
    offline = sum(1 for s in segs if s.status == "OFFLINE")
    return {"numSegments": len(segs), "segmentsMissingReplicas": missing,
            "segmentsOffline": offline}
