"""Minimal-disruption rebalancer: a journaled per-segment move engine.

Reference parity: helix/core/rebalance/TableRebalancer.java — the
reference walks the cluster from the current to the target assignment in
availability-preserving steps (bring the new replica ONLINE, wait for
the ExternalView to converge, only then drop the old one), never letting
a segment's live replica count fall below
``min(replication, minAvailableReplicas)``. Here every segment move is
an explicit state machine

    PLANNED -> LOADING -> WARMED -> ROUTED -> DRAINED -> DONE

journaled as JSON lines with the TaskQueue journal discipline
(append-only, flushed per line, last snapshot per key wins on replay,
torn tails skipped line-by-line, atomic tmp+rename compaction) so a
controller restart resumes a half-finished plan without re-moving
segments that already completed. The target replica is loaded AND
warmed first (``TableDataManager.add_segment`` runs the warmup hook
before publishing, so load implies warm by construction); only then is
the segment's assignment committed and the routing epoch advanced, and
only then is the source unloaded — closing the flip-before-load window
the one-shot ``maintenance.rebalance_table`` assignment flip has.

Determinism: journal lines carry no timestamps and job ids are
per-table counters, so a same-seed chaos run replays a byte-identical
journal. Seeded replay legs should run with
``pinot.controller.rebalance.max.parallel.moves = 1`` — parallel load
batches interleave journal appends nondeterministically.
"""
from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from pinot_tpu.controller.cluster_state import ClusterState, SegmentState
from pinot_tpu.utils.failpoints import fire

#: move state machine, in commit order
MOVE_STATES = ("PLANNED", "LOADING", "WARMED", "ROUTED", "DRAINED", "DONE",
               "CANCELLED")
_TERMINAL = {"DONE", "CANCELLED"}


@dataclass
class SegmentMove:
    """One segment's journey from its current replicas to the target."""
    segment: str
    table: str
    src: List[str] = field(default_factory=list)
    dst: List[str] = field(default_factory=list)
    state: str = "PLANNED"
    note: str = ""

    def entry(self, job_id: str) -> dict:
        e = {"kind": "move", "job": job_id, "segment": self.segment,
             "table": self.table, "from": list(self.src),
             "to": list(self.dst), "state": self.state}
        if self.note:
            e["note"] = self.note
        return e


class MoveJournal:
    """JSON-lines journal of job + move snapshots (TaskQueue discipline).

    Line kinds: ``{"kind": "job", "job", "table", "status"}`` and
    ``{"kind": "move", "job", "segment", "table", "from", "to",
    "state"}``. Replay keeps the LAST snapshot per job / per
    (job, segment); unparseable (torn) lines are skipped — a torn tail
    means that transition re-executes on resume (moves are idempotent),
    never a corrupt plan. Journal IO errors are swallowed: memory is the
    source of truth, the journal is the recovery record.
    """

    def __init__(self, path: Optional[str], max_bytes: int = 1 << 20):
        self.path = path
        self.max_bytes = max_bytes
        self._latest: "OrderedDict[tuple, dict]" = OrderedDict()
        self._fh = None
        self._lock = threading.Lock()

    @staticmethod
    def _key(e: dict) -> Optional[tuple]:
        kind = e.get("kind")
        if kind == "job":
            return ("job", e.get("job"))
        if kind == "move":
            return ("move", e.get("job"), e.get("segment"))
        return None

    def replay(self) -> List[dict]:
        """Last-snapshot-per-key entries, in first-seen key order."""
        latest: "OrderedDict[tuple, dict]" = OrderedDict()
        if self.path and os.path.exists(self.path):
            try:
                with open(self.path, "rb") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            e = json.loads(line)
                        except ValueError:
                            continue  # torn/corrupt line: skip, don't abort
                        key = self._key(e) if isinstance(e, dict) else None
                        if key is not None:
                            latest[key] = e
            except OSError:
                pass
        with self._lock:
            self._latest = latest
            return list(latest.values())

    def append(self, entry: dict) -> None:
        with self._lock:
            key = self._key(entry)
            if key is not None:
                self._latest.pop(key, None)
                self._latest[key] = entry
            if not self.path:
                return
            try:
                raw = json.dumps(entry, separators=(",", ":")).encode()
                # payload hook: an armed torn= policy truncates the line —
                # replay skips it and resume re-executes that transition
                raw = fire("controller.rebalance.journal", payload=raw,
                           kind=entry.get("kind"), job=entry.get("job"),
                           segment=entry.get("segment"),
                           state=entry.get("state") or entry.get("status"))
                if self._fh is None:
                    self._fh = open(self.path, "ab")
                self._fh.write(raw + b"\n")
                self._fh.flush()
                if self._fh.tell() > self.max_bytes:
                    self._compact_locked()
            except OSError:
                pass

    def _compact_locked(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for e in self._latest.values():
                f.write(json.dumps(e, separators=(",", ":")).encode() + b"\n")
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class RebalanceJob:
    """One async rebalance: a plan of SegmentMoves walked by the engine."""

    def __init__(self, job_id: str, table: str, moves: List[SegmentMove]):
        self.job_id = job_id
        self.table = table
        self.moves = moves
        self.status = "RUNNING"   # RUNNING | DONE | CANCELLED | FAILED
        self.error = ""
        self._cancel = threading.Event()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def cancel(self) -> None:
        self._cancel.set()

    def entry(self) -> dict:
        return {"kind": "job", "job": self.job_id, "table": self.table,
                "status": self.status}

    def progress(self) -> dict:
        by_state: Dict[str, int] = {}
        for m in self.moves:
            by_state[m.state] = by_state.get(m.state, 0) + 1
        out = {"jobId": self.job_id, "table": self.table,
               "status": self.status, "totalMoves": len(self.moves),
               "done": by_state.get("DONE", 0), "byState": by_state}
        if self.error:
            out["error"] = self.error
        return out


class Rebalancer:
    """The move engine: plans, executes, journals, resumes, cancels.

    load_fn(instance_id, table, seg_state) must load+warm the segment on
    the target and only return once it is servable (idempotent — resume
    may call it again). unload_fn(instance_id, table, segment_name)
    drops it from the source. commit_fn(table, {segment: [instances]})
    makes ONE routing-visible assignment change per batch (defaults to
    ``ClusterState.commit_moves`` — one persist, one notification, one
    routing-epoch bump). live_fn(instance_id) gates drains: a dead
    source is never unloaded over the wire, and the availability floor
    only counts live holders.
    """

    def __init__(self, state: ClusterState,
                 load_fn: Callable[[str, str, Optional[SegmentState]], None],
                 unload_fn: Callable[[str, str, str], None],
                 commit_fn: Optional[Callable[[str, Dict[str, List[str]]],
                                              None]] = None,
                 live_fn: Optional[Callable[[str], bool]] = None,
                 config=None, journal_path: Optional[str] = None,
                 metrics=None):
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.metrics import get_registry
        cfg = config or PinotConfiguration()
        self.state = state
        self.load_fn = load_fn
        self.unload_fn = unload_fn
        self.commit_fn = commit_fn or state.commit_moves
        self.live_fn = live_fn or self._default_live
        self.min_available = max(0, cfg.get_int(
            "pinot.controller.rebalance.min.available.replicas", 1))
        self.max_parallel = max(1, cfg.get_int(
            "pinot.controller.rebalance.max.parallel.moves", 4))
        self.journal = MoveJournal(journal_path, max_bytes=cfg.get_int(
            "pinot.controller.rebalance.journal.max.bytes", 1 << 20))
        self.metrics = metrics if metrics is not None \
            else get_registry("controller")
        #: seconds the source keeps serving AFTER its batch commits,
        #: before drain unloads it — queries routed on the pre-commit
        #: snapshot still land on a replica that holds the data
        #: (embedded clusters set this; watch-driven ones drain through
        #: the servers' own reconcile, which lags naturally)
        self.drain_grace_s = 0.0
        self.jobs: Dict[str, RebalanceJob] = {}
        self._lock = threading.Lock()
        self._threads: Dict[str, threading.Thread] = {}
        self._load_journaled_jobs()

    # -- construction / recovery --------------------------------------------
    def _default_live(self, instance_id: str) -> bool:
        inst = self.state.instances.get(instance_id)
        return inst is None or inst.enabled

    def _load_journaled_jobs(self) -> None:
        jobs_meta: Dict[str, dict] = {}
        moves_by_job: Dict[str, List[dict]] = {}
        for e in self.journal.replay():
            if e.get("kind") == "job":
                jobs_meta[e["job"]] = e
            elif e.get("kind") == "move":
                moves_by_job.setdefault(e["job"], []).append(e)
        for jid, meta in jobs_meta.items():
            moves = [SegmentMove(segment=e["segment"],
                                 table=e.get("table", meta.get("table", "")),
                                 src=list(e.get("from", [])),
                                 dst=list(e.get("to", [])),
                                 state=e.get("state", "PLANNED"),
                                 note=e.get("note", ""))
                     for e in moves_by_job.get(jid, [])]
            job = RebalanceJob(jid, meta.get("table", ""), moves)
            job.status = meta.get("status", "RUNNING")
            with self._lock:
                self.jobs[jid] = job

    def _next_job_id(self, table: str) -> str:
        # deterministic per-table counter (no uuid/time): same plan
        # sequence -> same job ids -> byte-identical journals
        prefix = f"rebalance_{table}_"
        n = 0
        # lint: unlocked(caller _register holds self._lock; the lock is not reentrant)
        for jid in self.jobs:
            if jid.startswith(prefix):
                try:
                    n = max(n, int(jid[len(prefix):]) + 1)
                except ValueError:
                    pass
        return f"{prefix}{n}"

    # -- planning ------------------------------------------------------------
    def plan(self, table: str, moves: Dict[str, dict]) -> List[SegmentMove]:
        """moves: {segment: {"from": [...], "to": [...]}} (the
        maintenance.rebalance_table dry-run shape). Sorted by segment
        name for a deterministic execution order."""
        return [SegmentMove(segment=name, table=table,
                            src=list(mv.get("from", [])),
                            dst=list(mv.get("to", [])))
                for name, mv in sorted(moves.items())]

    # -- job lifecycle -------------------------------------------------------
    def start(self, table: str, moves: Dict[str, dict]) -> str:
        """Plan + execute asynchronously; returns the job id."""
        job = self._register(table, moves)
        t = threading.Thread(target=self._run_job, args=(job,), daemon=True,
                             name=f"rebalance-{job.job_id}")
        with self._lock:
            self._threads[job.job_id] = t
        t.start()
        return job.job_id

    def run(self, table: str, moves: Dict[str, dict]) -> RebalanceJob:
        """Plan + execute synchronously; returns the finished job."""
        return self.execute(self._register(table, moves))

    def _register(self, table: str, moves: Dict[str, dict]) -> RebalanceJob:
        with self._lock:
            job = RebalanceJob(self._next_job_id(table), table,
                               self.plan(table, moves))
            self.jobs[job.job_id] = job
        # journal the WHOLE plan up front: a crash right after start
        # still leaves resume() the full move list, not a truncated one
        self.journal.append(job.entry())
        for m in job.moves:
            self.journal.append(m.entry(job.job_id))
        return job

    def _run_job(self, job: RebalanceJob) -> None:
        try:
            self.execute(job)
        except Exception as exc:  # noqa: BLE001 — async job must not die silently
            # in-memory FAILED only: the journal keeps RUNNING so a
            # restart resumes the plan instead of abandoning it
            job.status = "FAILED"
            job.error = f"{type(exc).__name__}: {exc}"

    def status(self, job_id: str) -> Optional[dict]:
        with self._lock:
            job = self.jobs.get(job_id)
        return None if job is None else job.progress()

    def cancel(self, job_id: str) -> bool:
        with self._lock:
            job = self.jobs.get(job_id)
        if job is None or job.status != "RUNNING":
            return False
        job.cancel()
        return True

    def wait(self, job_id: str, timeout: float = 30.0) -> Optional[dict]:
        with self._lock:
            t = self._threads.get(job_id)
        if t is not None:
            t.join(timeout=timeout)
        return self.status(job_id)

    def resume(self) -> List[str]:
        """Re-execute journaled RUNNING jobs (controller restart).
        DONE/CANCELLED moves are skipped; LOADING redoes its idempotent
        loads; WARMED goes straight to commit; ROUTED drains only."""
        resumed = []
        with self._lock:
            jids = sorted(self.jobs)
        for jid in jids:
            job = self.jobs[jid]  # lint: unlocked(jobs entries are never removed; the snapshot above fixes the iteration set)
            if job.status == "RUNNING":
                self.execute(job)
                resumed.append(jid)
        return resumed

    # -- the engine ----------------------------------------------------------
    def execute(self, job: RebalanceJob) -> RebalanceJob:
        pending = [m for m in job.moves if m.state not in _TERMINAL]
        while pending:
            if job.cancelled:
                # consistent prefix: finished batches stay applied,
                # unstarted moves are cancelled whole
                for m in pending:
                    self._set_state(job, m, "CANCELLED")
                job.status = "CANCELLED"
                self.journal.append(job.entry())
                return job
            batch = pending[:self.max_parallel]
            pending = pending[self.max_parallel:]
            self._run_batch(job, batch)
        job.status = "DONE"
        self.journal.append(job.entry())
        return job

    def _run_batch(self, job: RebalanceJob, batch: List[SegmentMove]) -> None:
        # phase 1: load+warm every target replica in the batch
        to_load = [m for m in batch if m.state in ("PLANNED", "LOADING")]
        if len(to_load) > 1 and self.max_parallel > 1:
            with ThreadPoolExecutor(max_workers=len(to_load)) as pool:
                # list() re-raises the first load failure in this thread
                list(pool.map(lambda m: self._load_move(job, m), to_load))
        else:
            for m in to_load:
                self._load_move(job, m)
        # phase 2: ONE assignment commit = one routing-epoch bump for
        # the whole batch (resumed ROUTED moves are already committed)
        warmed = [m for m in batch if m.state == "WARMED"]
        if warmed:
            assignment = {m.segment: list(m.dst) for m in warmed}
            fire("controller.rebalance.move", table=job.table, stage="commit",
                 segment=warmed[0].segment)
            self.commit_fn(job.table, assignment)
            for m in warmed:
                self._set_state(job, m, "ROUTED")
        # phase 3: drain sources, never below the availability floor
        if warmed and self.drain_grace_s > 0:
            import time as _time
            _time.sleep(self.drain_grace_s)
        for m in batch:
            if m.state == "ROUTED":
                self._drain_move(job, m)
            elif m.state == "DRAINED":
                # resume: crashed between DRAINED and DONE
                self._set_state(job, m, "DONE")
                self.metrics.add_meter("rebalance_moves_completed")

    def _load_move(self, job: RebalanceJob, m: SegmentMove) -> None:
        self._set_state(job, m, "LOADING")
        st = self._seg_state(m.table, m.segment)
        if st is None:
            m.note = "segment gone"
        else:
            for inst in sorted(set(m.dst) - set(m.src)):
                fire("controller.rebalance.move", segment=m.segment,
                     table=m.table, instance=inst, stage="load")
                self.load_fn(inst, m.table, st)
        self._set_state(job, m, "WARMED")

    def _drain_move(self, job: RebalanceJob, m: SegmentMove) -> None:
        floor = max(1, min(len(m.dst), self.min_available))
        holders = set(m.src) | set(m.dst)
        for inst in sorted(set(m.src) - set(m.dst)):
            fire("controller.rebalance.move", segment=m.segment,
                 table=m.table, instance=inst, stage="drain")
            live_remaining = [i for i in holders - {inst} if self.live_fn(i)]
            if len(live_remaining) < floor:
                m.note = f"source {inst} retained (availability floor)"
                continue
            if self.live_fn(inst):
                try:
                    self.unload_fn(inst, m.table, m.segment)
                except Exception:  # noqa: BLE001 — drain is best-effort
                    m.note = f"unload failed on {inst}"
            holders.discard(inst)
        self._set_state(job, m, "DRAINED")
        self._set_state(job, m, "DONE")
        self.metrics.add_meter("rebalance_moves_completed")

    # -- helpers -------------------------------------------------------------
    def _set_state(self, job: RebalanceJob, m: SegmentMove,
                   state: str) -> None:
        m.state = state
        self.journal.append(m.entry(job.job_id))

    def _seg_state(self, table: str, name: str) -> Optional[SegmentState]:
        for s in self.state.table_segments(table):
            if s.name == name:
                return s
        return None

    def close(self) -> None:
        self.journal.close()


def make_staged_load_fn(state: ClusterState,
                        ack_fn: Callable[[str, str, str], bool],
                        timeout_s: float = 30.0,
                        poll_s: float = 0.05) -> Callable:
    """load_fn for watch-driven clusters (roles.py): stage the replica
    in ClusterState (servers reconcile ``staged`` segments and load+warm
    them, brokers route by ``instances`` only), then wait for the
    server's load ack. ack_fn(table, segment, instance) -> loaded?"""
    import time as _time

    def load(instance_id: str, table: str,
             st: Optional[SegmentState]) -> None:
        if st is None:
            return
        state.stage_replicas(table, {st.name: [instance_id]})
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if ack_fn(table, st.name, instance_id):
                return
            _time.sleep(poll_s)
        raise TimeoutError(
            f"segment {st.name} not acked on {instance_id} "
            f"within {timeout_s}s")

    return load
