"""Automatic failure repair: the RebalanceChecker / periodic-repair analog.

Reference parity: pinot-controller's RebalanceChecker +
SegmentStatusChecker periodic tasks — watch instance liveness, mark a
dead instance's segments under-replicated, and re-replicate them onto
healthy tenant-matched instances through the same minimal-disruption
move engine a manual rebalance uses. ``segments_missing_replicas``
draining back to zero is the convergence signal.

Debounce: an instance only counts as failed once its heartbeat age has
exceeded ``pinot.controller.repair.grace.seconds`` on TWO consecutive
check ticks — a flapping instance (stale one tick, heartbeating the
next) never triggers replica churn, and an instance that returns after
repair simply drops out of the assignment (its copies were already
replaced; nothing moves back, so rejoin costs zero moves).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Set

from pinot_tpu.controller import maintenance
from pinot_tpu.controller.cluster_state import ClusterState
from pinot_tpu.controller.rebalancer import Rebalancer
from pinot_tpu.utils.failpoints import fire


def update_replication_gauges(state: ClusterState, metrics=None,
                              live: Optional[Set[str]] = None
                              ) -> Dict[str, Dict[str, int]]:
    """SegmentStatusChecker gauges: per-table
    ``segments_missing_replicas{table=}`` / ``segments_offline{table=}``
    on the controller registry (the /debug/health ``replication``
    subsystem and /cluster/health read these). Returns the per-table
    status dicts. ``live``: when given, only replicas on live instances
    count toward replication."""
    if metrics is None:
        from pinot_tpu.utils.metrics import get_registry
        metrics = get_registry("controller")
    out: Dict[str, Dict[str, int]] = {}
    for cfg in list(state.tables.values()):
        t = cfg.table_name_with_type
        st = maintenance.segment_status(
            state, t, max(1, cfg.retention.replication), live=live)
        metrics.set_gauge("segments_missing_replicas",
                          st["segmentsMissingReplicas"],
                          labels={"table": t})
        metrics.set_gauge("segments_offline", st["segmentsOffline"],
                          labels={"table": t})
        out[t] = st
    return out


class RepairChecker:
    """Periodic repair loop: heartbeat ages in, repair moves out.

    heartbeat_ages_fn() -> {instance_id: seconds since last heartbeat}.
    Instances absent from the map are treated as live (statically wired
    deployments report no ages)."""

    def __init__(self, state: ClusterState, rebalancer: Rebalancer,
                 heartbeat_ages_fn: Callable[[], Dict[str, float]],
                 config=None, metrics=None):
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = config or PinotConfiguration()
        self.state = state
        self.rebalancer = rebalancer
        self.heartbeat_ages_fn = heartbeat_ages_fn
        self.grace_s = cfg.get_float(
            "pinot.controller.repair.grace.seconds", 30.0)
        self.enabled = cfg.get_bool("pinot.controller.repair.enabled", True)
        if metrics is None:
            from pinot_tpu.utils.metrics import get_registry
            metrics = get_registry("controller")
        self.metrics = metrics
        #: instance -> consecutive stale ticks (the debounce state)
        self._stale_streak: Dict[str, int] = {}
        self._ages: Dict[str, float] = {}

    def stale_instances(self) -> Set[str]:
        """One debounce tick: update streaks from current heartbeat
        ages, return instances stale for >= 2 consecutive ticks."""
        ages = dict(self.heartbeat_ages_fn() or {})
        stale: Set[str] = set()
        for iid, age in ages.items():
            if age > self.grace_s:
                n = self._stale_streak.get(iid, 0) + 1
                self._stale_streak[iid] = n
                if n >= 2:
                    stale.add(iid)
            else:
                # heartbeat returned: clear the streak — a flapping
                # instance never accumulates enough to trigger churn
                self._stale_streak.pop(iid, None)
        self._ages = ages
        return stale

    def check_once(self) -> dict:
        """One repair pass. Returns {"stale": [...], "repaired":
        {table: [segments]}} and leaves the replication gauges updated
        (with repairs applied, so convergence reads as missing == 0)."""
        if not self.enabled:
            return {"stale": [], "repaired": {}}
        stale = self.stale_instances()
        repaired: Dict[str, list] = {}
        if stale:
            for cfg_t in list(self.state.tables.values()):
                segs = self._repair_table(cfg_t, stale)
                if segs:
                    repaired[cfg_t.table_name_with_type] = segs
        live = {i.instance_id for i in self.state.server_instances()
                if i.instance_id not in stale}
        update_replication_gauges(self.state, metrics=self.metrics,
                                  live=live)
        return {"stale": sorted(stale), "repaired": repaired}

    def _repair_table(self, cfg_t, stale: Set[str]) -> list:
        table = cfg_t.table_name_with_type
        expected = max(1, cfg_t.retention.replication)
        # healthy tenant-matched candidate pool, residency-preferred:
        # a target already serving bytes of this table warms fastest
        candidates = [
            i for i in self.state.server_instances(cfg_t.tenants.server)
            if i.enabled and i.instance_id not in stale
            and self._ages.get(i.instance_id, 0.0) <= self.grace_s]
        moves: Dict[str, dict] = {}
        for seg in self.state.table_segments(table):
            live = [i for i in seg.instances if i not in stale]
            if len(live) >= expected:
                continue
            if not seg.dir_path:
                continue  # no deep-store / surviving dir to replicate from
            pool = sorted(
                (c for c in candidates if c.instance_id not in live),
                key=lambda c: (-c.residency.get(table, 0), c.instance_id))
            targets = [c.instance_id for c in pool[:expected - len(live)]]
            if not targets:
                continue
            try:
                for tgt in targets:
                    fire("controller.repair.replicate", segment=seg.name,
                         table=table, target=tgt)
            except Exception:  # noqa: BLE001 — chaos/skip: retry next tick
                continue
            moves[seg.name] = {"from": list(seg.instances),
                               "to": live + targets}
        if not moves:
            return []
        job = self.rebalancer.run(table, moves)
        if job.status != "DONE":
            return []
        self.metrics.add_meter("repair_replications", len(moves))
        return sorted(moves)
