"""Distributed minion task fabric — controller side.

Reference parity: pinot-controller minion/PinotTaskManager:84 bridging
task *generators* to distributed minion *executors* through the Helix
Task Framework. Without Helix, the controller owns a durable task queue
(journaled like the warmup FingerprintLog — JSON-lines, reloaded at
boot, compacted atomically) and hands work to minion workers through
LEASES: a worker polls for tasks matching its declared task types,
renews its lease with heartbeats while running, and an expired lease
requeues the task with capped exponential backoff. The generate/execute
split of controller/tasks.py is unchanged — generators still scan
ClusterState; execution just moved off the controller's threads.

Task state machine (exposed over coordination ops + the controller
HTTP API)::

    PENDING --lease--> LEASED --renew--> RUNNING --complete--> COMPLETED
       ^                  |                 |
       +---- requeue with backoff ---------+   (fail/expire, attempts
       |                                        remaining)
       +---- fail/expire, attempts exhausted -----------------> FAILED
    cancel: PENDING -> CANCELLED immediately; LEASED/RUNNING set
    cancel_requested, the next heartbeat tells the worker to abort and
    its fail report lands the task in CANCELLED.

Commit protocol: a finished task's output segments are uploaded to the
deep store by the worker, then committed through ONE controller-side
``segment_replace`` — an atomic ClusterState swap (adds upserted +
removes dropped under a single lock/persist/notify), which moves the
broker routing epoch (invalidating PR-1/2 result caches) and triggers
server reconcile loads (which warm the new segment via PR-2
SegmentWarmup before it serves). The swap is IDEMPOTENT: replaying it
(crashed worker, re-leased task) upserts the same deterministic segment
names and no-ops the already-removed ones, so crash-mid-commit never
duplicates or loses segments.

Failpoint sites (ROADMAP open item — controller coordination chaos):
``controller.task.assign`` (lease grant), ``controller.task.lease.renew``
(heartbeat), ``controller.segment.replace`` (the swap). The worker-side
``minion.task.execute`` site lives in minion/worker.py.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from pinot_tpu.controller.cluster_state import ClusterState, SegmentState
from pinot_tpu.controller.tasks import TaskConfig
from pinot_tpu.utils.failpoints import fire

log = logging.getLogger(__name__)

#: task states
PENDING = "PENDING"
LEASED = "LEASED"
RUNNING = "RUNNING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"

TERMINAL = (COMPLETED, FAILED, CANCELLED)
ACTIVE = (PENDING, LEASED, RUNNING)


@dataclass
class TaskEntry:
    """One task's full lifecycle record (the Helix TaskConfig + context
    ZNode analog). Wall-clock times throughout — the journal must stay
    meaningful across a controller restart."""
    task_id: str
    task_type: str
    table: str
    segments: List[str] = field(default_factory=list)
    params: Dict[str, Any] = field(default_factory=dict)
    state: str = PENDING
    worker: Optional[str] = None
    lease_expiry: float = 0.0
    attempts: int = 0
    max_attempts: int = 3
    #: lease precedence: higher leases first (within a priority tier the
    #: queue round-robins over tables, then FIFO). Defaults to 0; set
    #: explicitly or via a ``priority`` task param.
    priority: int = 0
    #: backoff gate: a requeued task is not leasable before this time
    not_before: float = 0.0
    cancel_requested: bool = False
    progress: str = ""
    result: Optional[dict] = None
    error: Optional[str] = None
    created_at: float = 0.0
    updated_at: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TaskEntry":
        return cls(**d)

    def to_config(self) -> TaskConfig:
        return TaskConfig(self.task_type, self.table, list(self.segments),
                          dict(self.params), task_id=self.task_id)


class TaskQueue:
    """Durable lease-based task queue.

    journal_path: append-only JSON-lines of task-entry snapshots, one
    per state transition; reloaded at construction (last snapshot per id
    wins), so PENDING/LEASED tasks survive a controller restart — a
    reloaded LEASED task keeps its (wall-clock) lease and requeues
    through the normal expiry sweep. Compacts to a snapshot of live
    entries via atomic tmp+rename once it outgrows journal_max_bytes;
    torn tail lines degrade to the previous snapshot of that task.
    Journal I/O failures are swallowed: the in-memory queue is the
    source of truth, persistence is crash insurance.
    """

    def __init__(self, journal_path: Optional[str] = None,
                 lease_ttl_s: float = 30.0, max_attempts: int = 3,
                 backoff_s: float = 1.0, backoff_cap_s: float = 30.0,
                 journal_max_bytes: int = 1 << 20, max_done: int = 256,
                 metrics=None,
                 tenant_weight_of: Optional[Callable[[str], float]] = None):
        self.lease_ttl_s = float(lease_ttl_s)
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.max_done = max(1, int(max_done))
        self._tasks: "Dict[str, TaskEntry]" = {}
        self._lock = threading.Lock()
        #: per-table virtual lease time for tenant-weighted fairness:
        #: each lease advances the table's clock by 1/weight, and the
        #: slowest clock goes first — weight 2.0 tables lease twice as
        #: often as weight 1.0 under contention (the minion analog of
        #: the per-tenant weighted-fair query scheduler). Weight 1.0
        #: everywhere degenerates to the old plain round-robin.
        self._table_vtime: Dict[str, float] = {}
        #: new tables join at the floor (the last-served table's clock),
        #: not at 0 — a late-arriving table gets round-robin parity, not
        #: a catch-up burst over everyone's backlog
        self._vtime_floor = 0.0
        self._tenant_weight_of = tenant_weight_of
        self._metrics = metrics
        self.journal_path = journal_path
        self.journal_max_bytes = max(4096, int(journal_max_bytes))
        self._journal_file = None
        self._journal_bytes = 0
        if journal_path:
            self._replay_journal()

    # -- journal (FingerprintLog discipline) ---------------------------
    def _replay_journal(self) -> None:
        try:
            with open(self.journal_path, encoding="utf-8",
                      errors="replace") as f:
                lines = f.readlines()
        except OSError:
            return  # first boot or unreadable: start empty
        for raw in lines:
            try:
                e = TaskEntry.from_dict(json.loads(raw))
            except (ValueError, TypeError, KeyError):
                continue  # torn/corrupt line: keep the rest
            self._tasks[e.task_id] = e

    def _journal_locked(self, entry: TaskEntry) -> None:
        if not self.journal_path:
            return
        line = json.dumps(entry.to_dict()) + "\n"
        try:
            if self._journal_file is None:
                self._journal_file = open(self.journal_path, "a",
                                          encoding="utf-8")
                self._journal_bytes = os.path.getsize(self.journal_path)
            self._journal_file.write(line)
            self._journal_file.flush()
            self._journal_bytes += len(line.encode("utf-8"))
            if self._journal_bytes > self.journal_max_bytes:
                self._compact_locked()
        except OSError:
            log.debug("task journal write failed", exc_info=True)

    def _compact_locked(self) -> None:
        if self._journal_file is not None:
            self._journal_file.close()
            self._journal_file = None
        tmp = self.journal_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for e in self._tasks.values():
                f.write(json.dumps(e.to_dict()) + "\n")
        os.replace(tmp, self.journal_path)
        self._journal_bytes = os.path.getsize(self.journal_path)

    def close(self) -> None:
        with self._lock:
            if self._journal_file is not None:
                try:
                    self._journal_file.close()
                except OSError:
                    pass
                self._journal_file = None

    # -- helpers -------------------------------------------------------
    def _touch_locked(self, e: TaskEntry) -> None:
        e.updated_at = time.time()
        self._journal_locked(e)
        self._set_depth_locked()

    def _set_depth_locked(self) -> None:
        if self._metrics is not None:
            depth = sum(1 for t in self._tasks.values()
                        if t.state in ACTIVE)
            self._metrics.set_gauge("task_queue_depth", depth)

    def _meter(self, name: str, task_type: str) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(name, labels={"taskType": task_type})

    def _evict_done_locked(self) -> None:
        done = [e for e in self._tasks.values() if e.state in TERMINAL]
        if len(done) <= self.max_done:
            return
        done.sort(key=lambda e: e.updated_at)
        for e in done[: len(done) - self.max_done]:
            del self._tasks[e.task_id]

    # -- queue API -----------------------------------------------------
    def submit(self, task: TaskConfig,
               max_attempts: Optional[int] = None,
               priority: Optional[int] = None) -> TaskEntry:
        task_id = task.task_id or \
            f"Task_{task.task_type}_{uuid.uuid4().hex[:12]}"
        if priority is None:
            try:
                priority = int(task.params.get("priority", 0))
            except (TypeError, ValueError):
                priority = 0
        with self._lock:
            existing = self._tasks.get(task_id)
            if existing is not None:
                return existing  # idempotent re-submit
            e = TaskEntry(
                task_id=task_id, task_type=task.task_type, table=task.table,
                segments=list(task.segments), params=dict(task.params),
                max_attempts=max_attempts or self.max_attempts,
                priority=priority, created_at=time.time())
            self._tasks[task_id] = e
            self._touch_locked(e)
            return e

    def active_segments(self, table: str,
                        task_type: Optional[str] = None) -> set:
        """Every segment name covered by ANY active task of this table
        (optionally narrowed to one task type). Generators must not emit
        input sets that OVERLAP an in-flight task — exact-set dedupe
        alone would admit a superset (a new segment sealed mid-flight)
        whose execution re-processes the in-flight task's inputs, e.g.
        migrating the same realtime rows into the OFFLINE table twice.
        The default spans ALL task types because every executor
        consumes-and-retires its inputs: a MergeRollupTask and a
        PurgeTask racing over the same segments would each republish the
        rows once — double-counted forever."""
        with self._lock:
            out: set = set()
            for e in self._tasks.values():
                if e.state in ACTIVE and e.table == table \
                        and (task_type is None or e.task_type == task_type):
                    out.update(e.segments)
            return out

    def lease(self, worker: str,
              task_types: Optional[List[str]] = None,
              lease_ttl_s: Optional[float] = None) -> Optional[TaskEntry]:
        """Grant one leasable PENDING task matching the worker's declared
        task types. Lease order is (priority desc, tenant-weighted
        round-robin over tables, FIFO): within the highest waiting
        priority tier the table with the SLOWEST virtual lease clock
        goes first, and each grant advances the winner's clock by
        1/tenant-weight — so a flood of one table's tasks cannot starve
        another table's, and a weight-2 tenant's tables lease twice as
        often as weight-1 under contention. Within a table it is
        oldest-first, as before. Sweeps expired leases first so a
        polling worker (not just the cadence loop) recovers crashed
        peers' work."""
        now = time.time()
        self.expire_leases(now)
        ttl = lease_ttl_s if lease_ttl_s is not None else self.lease_ttl_s
        with self._lock:
            candidates = sorted(
                (e for e in self._tasks.values()
                 if e.state == PENDING and e.not_before <= now
                 and (not task_types or e.task_type in task_types)),
                key=lambda e: (-e.priority,
                               self._table_vtime.get(e.table,
                                                     self._vtime_floor),
                               e.created_at, e.task_id))
            if not candidates:
                return None
            e = candidates[0]
            v = self._table_vtime.get(e.table, self._vtime_floor)
            self._vtime_floor = v
            w = 1.0
            if self._tenant_weight_of is not None:
                try:
                    w = float(self._tenant_weight_of(e.table) or 1.0)
                except Exception:  # noqa: BLE001 — fairness, not safety
                    w = 1.0
            self._table_vtime[e.table] = v + 1.0 / max(w, 1e-6)
            # chaos hook: delay/fail the grant itself (a raise leaves the
            # task PENDING — the lease was never handed out)
            fire("controller.task.assign", task_id=e.task_id,
                 worker=worker, task_type=e.task_type)
            e.state = LEASED
            e.worker = worker
            e.lease_expiry = now + ttl
            e.attempts += 1
            e.progress = ""
            e.error = None
            self._touch_locked(e)
            return e

    def renew(self, task_id: str, worker: str,
              progress: Optional[str] = None) -> dict:
        """Heartbeat: extend the lease, record progress, report whether a
        cancel was requested. An unknown/foreign lease returns ok=False —
        the worker must abandon the task (someone else owns it now)."""
        fire("controller.task.lease.renew", task_id=task_id, worker=worker)
        with self._lock:
            e = self._tasks.get(task_id)
            if e is None or e.worker != worker \
                    or e.state not in (LEASED, RUNNING):
                return {"ok": False, "cancelled": False}
            e.state = RUNNING
            e.lease_expiry = time.time() + self.lease_ttl_s
            if progress is not None:
                e.progress = progress
            self._touch_locked(e)
            return {"ok": True, "cancelled": e.cancel_requested}

    def complete(self, task_id: str, worker: str,
                 result: Optional[dict] = None) -> bool:
        with self._lock:
            e = self._tasks.get(task_id)
            if e is None or e.worker != worker \
                    or e.state not in (LEASED, RUNNING):
                return False
            e.state = COMPLETED
            e.result = result or {}
            self._touch_locked(e)
            self._evict_done_locked()
        self._meter("minion_tasks_completed", e.task_type)
        return True

    def fail(self, task_id: str, worker: str, error: str = "",
             cancelled: bool = False) -> bool:
        with self._lock:
            e = self._tasks.get(task_id)
            if e is None or e.worker != worker \
                    or e.state not in (LEASED, RUNNING):
                return False
            self._requeue_or_fail_locked(e, error, cancelled=cancelled)
        return True

    def cancel(self, task_id: str) -> Optional[str]:
        """PENDING cancels immediately; LEASED/RUNNING flags the worker
        through its next heartbeat. Returns the resulting state."""
        with self._lock:
            e = self._tasks.get(task_id)
            if e is None:
                return None
            if e.state == PENDING:
                e.state = CANCELLED
                self._touch_locked(e)
            elif e.state in (LEASED, RUNNING):
                e.cancel_requested = True
                self._touch_locked(e)
            return e.state

    def expire_leases(self, now: Optional[float] = None) -> List[str]:
        """Requeue (or terminally fail) tasks whose lease ran out — the
        crashed-worker recovery path. Each expiry requeues EXACTLY once:
        the state transition back to PENDING happens under the lock."""
        now = now if now is not None else time.time()
        expired = []
        with self._lock:
            for e in self._tasks.values():
                if e.state in (LEASED, RUNNING) and e.lease_expiry <= now:
                    self._requeue_or_fail_locked(
                        e, f"lease expired on worker {e.worker}")
                    expired.append(e.task_id)
        return expired

    def _requeue_or_fail_locked(self, e: TaskEntry, error: str,
                                cancelled: bool = False) -> None:
        e.error = error
        e.worker = None
        e.lease_expiry = 0.0
        if cancelled or e.cancel_requested:
            e.state = CANCELLED
        elif e.attempts >= e.max_attempts:
            e.state = FAILED
            self._meter("minion_tasks_failed", e.task_type)
        else:
            # capped exponential backoff: attempt N retries after
            # min(backoff * 2^(N-1), cap)
            e.state = PENDING
            e.not_before = time.time() + min(
                self.backoff_s * (2 ** (e.attempts - 1)),
                self.backoff_cap_s)
            self._meter("minion_tasks_retried", e.task_type)
        self._touch_locked(e)
        if e.state in TERMINAL:
            self._evict_done_locked()

    # -- introspection -------------------------------------------------
    def get(self, task_id: str) -> Optional[TaskEntry]:
        with self._lock:
            return self._tasks.get(task_id)

    def list(self, state: Optional[str] = None) -> List[TaskEntry]:
        with self._lock:
            out = [e for e in self._tasks.values()
                   if state is None or e.state == state]
        return sorted(out, key=lambda e: (e.created_at, e.task_id))

    def __len__(self) -> int:
        with self._lock:
            return len(self._tasks)


class TaskManager:
    """Queue + generator cadence + the atomic segment-replace commit."""

    def __init__(self, state: ClusterState, config=None,
                 journal_path: Optional[str] = None, metrics=None,
                 on_replace: Optional[Callable] = None):
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.metrics import get_registry
        cfg = config or PinotConfiguration()
        self.state = state
        self.config = cfg
        self._metrics = metrics if metrics is not None \
            else get_registry("controller")
        self.queue = TaskQueue(
            journal_path=journal_path,
            lease_ttl_s=cfg.get_float("pinot.controller.task.lease.seconds"),
            max_attempts=cfg.get_int("pinot.controller.task.max.attempts"),
            backoff_s=cfg.get_float(
                "pinot.controller.task.retry.backoff.seconds"),
            backoff_cap_s=cfg.get_float(
                "pinot.controller.task.retry.backoff.cap.seconds"),
            journal_max_bytes=cfg.get_int(
                "pinot.controller.task.journal.max.bytes"),
            metrics=self._metrics,
            tenant_weight_of=self._tenant_weight)
        self.generators_enabled = cfg.get_bool(
            "pinot.controller.task.generators.enabled")
        #: injectable workload source for the auto star-tree generator
        #: (tests substitute a canned registry; production reads the
        #: server-role rollup that backs /debug/workload)
        from pinot_tpu.health.workload import get_workload
        self.workload_provider: Callable = lambda: get_workload("server")
        #: callback(adds: [SegmentState], removes: [(table, name)]) fired
        #: AFTER a segment-replace commits — embedded harnesses
        #: (MiniCluster) push the swap into their servers/routing with it
        self.on_replace = on_replace
        #: fast idempotency path for replayed commits, bounded FIFO (the
        #: state-level swap is idempotent anyway — eviction only costs a
        #: replayed commit one extra no-op epoch move, never correctness)
        self._applied: "OrderedDict[str, None]" = OrderedDict()
        self._applied_max = 1024
        self._replace_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _tenant_weight(self, physical_table: str) -> float:
        """Lease-fairness weight of a physical table = its tenant
        config's scheduler weight (TableConfig.tenants.weight) — minion
        capacity follows the same per-tenant shares as query capacity."""
        base = physical_table.rsplit("_", 1)[0]
        cfg = self.state.tables.get(base)
        tenants = getattr(cfg, "tenants", None) if cfg is not None else None
        return float(getattr(tenants, "weight", 1.0) or 1.0)

    # -- scheduler cadence ---------------------------------------------
    def run_once(self) -> Dict[str, int]:
        """One cadence tick: sweep expired leases, then feed the queue
        from the generators (deduped against active tasks)."""
        expired = self.queue.expire_leases()
        generated = 0
        if self.generators_enabled:
            generated = self.generate_tasks()
        return {"expired": len(expired), "generated": generated}

    # -- generators (ref PinotTaskGenerator registry) -------------------
    def _gen_merge_rollup(self, cfg, params) -> List[TaskConfig]:
        from pinot_tpu.controller.tasks import generate_merge_rollup_tasks
        return generate_merge_rollup_tasks(
            self.state, f"{cfg.name}_OFFLINE",
            max_docs_per_merged=int(
                params.get("maxDocsPerMergedSegment", 5_000_000)),
            min_segments=int(params.get("minSegments", 2)))

    def _gen_realtime_to_offline(self, cfg, params) -> List[TaskConfig]:
        from pinot_tpu.controller.tasks import (
            generate_realtime_to_offline_tasks)
        return generate_realtime_to_offline_tasks(
            self.state, cfg.name,
            max_segments_per_task=int(params.get("maxSegmentsPerTask", 16)),
            min_segments=int(params.get("minSegments", 1)))

    def _gen_purge(self, cfg, params) -> List[TaskConfig]:
        if not params.get("purgePredicate"):
            return []  # opt-in without a predicate: nothing to drop
        from pinot_tpu.controller.tasks import generate_purge_tasks
        return generate_purge_tasks(
            self.state, f"{cfg.name}_OFFLINE",
            max_segments_per_task=int(params.get("maxSegmentsPerTask", 16)))

    def _gen_startree_build(self, cfg, params) -> List[TaskConfig]:
        # no tree config anywhere -> nothing the executor could build;
        # upsert tables never build (TableConfig.validate rejects the
        # combination — pre-agg records cannot apply validDocIds)
        if cfg.upsert:
            return []
        if not (params.get("starTreeIndexConfigs")
                or cfg.indexing.star_tree_configs):
            return []
        from pinot_tpu.controller.tasks import generate_startree_build_tasks
        types = params.get("tableTypes") or ["REALTIME", "OFFLINE"]
        out: List[TaskConfig] = []
        for t in types:
            out += generate_startree_build_tasks(
                self.state, f"{cfg.name}_{t}",
                max_segments_per_task=int(
                    params.get("maxSegmentsPerTask", 16)))
        return out

    def _gen_clp_compaction(self, cfg, params) -> List[TaskConfig]:
        # nothing to compact without configured log columns (task params
        # or table indexing config)
        if not (params.get("clpColumns") or cfg.indexing.clp_columns):
            return []
        from pinot_tpu.controller.tasks import generate_clp_compaction_tasks
        types = params.get("tableTypes") or ["REALTIME", "OFFLINE"]
        out: List[TaskConfig] = []
        for t in types:
            out += generate_clp_compaction_tasks(
                self.state, f"{cfg.name}_{t}",
                max_segments_per_task=int(
                    params.get("maxSegmentsPerTask", 16)))
        return out

    def _gen_auto_startree(self, cfg, params) -> List[TaskConfig]:
        """Workload-driven star-tree scheduling: only schedule builds
        for tables the observed workload rollup (/debug/workload) shows
        as HOT — repeated plan fingerprints above a cost floor. Opt-in
        via task_configs["AutoStarTreeTask"]; emits plain
        StarTreeBuildTask configs, so the executor/commit path is
        identical to explicitly scheduled builds."""
        if cfg.upsert:
            return []
        if not (params.get("starTreeIndexConfigs")
                or cfg.indexing.star_tree_configs):
            return []
        reg = self.workload_provider()
        min_cost = float(params.get("minCostMs", 100.0))
        min_queries = int(params.get("minQueries", 2))
        names = {cfg.name, f"{cfg.name}_OFFLINE", f"{cfg.name}_REALTIME"}
        hot = [w for w in reg.top(int(params.get("topK", 20)), by="cost_ms")
               if w["table"] in names and w["costMs"] >= min_cost
               and w["queries"] >= min_queries]
        if not hot:
            return []
        return self._gen_startree_build(cfg, params)

    #: task-config key -> generator method; a table opts in per type via
    #: ``TableConfig.task_configs[<task type>]`` (taskTypeConfigsMap)
    GENERATORS = {
        "MergeRollupTask": _gen_merge_rollup,
        "RealtimeToOfflineSegmentsTask": _gen_realtime_to_offline,
        "PurgeTask": _gen_purge,
        "StarTreeBuildTask": _gen_startree_build,
        "ClpCompactionTask": _gen_clp_compaction,
        "AutoStarTreeTask": _gen_auto_startree,
    }

    def generate_tasks(self) -> int:
        """Run every registered generator over every table whose config
        opts in via ``taskTypeConfigsMap``-style params — the
        PinotTaskGenerator scan, feeding the durable queue instead of a
        local pool. Emitted tasks inherit the table's per-type config
        params (e.g. purgePredicate) and dedupe against active tasks
        covering the same input set, so the cadence loop is idempotent
        while work is in flight. The existing executors (controller/
        tasks.py) run whatever comes out — generators only decide WHAT
        to scan, never how to execute."""
        n = 0
        #: one active-set snapshot per TABLE across ALL task types — the
        #: queue scan is O(entries) under the queue lock (per-candidate
        #: re-scans would make a many-chunk tick quadratic), and every
        #: executor consumes-and-retires its inputs, so two task types
        #: over the same segments would duplicate rows
        busy: Dict[str, set] = {}
        for cfg in list(self.state.tables.values()):
            task_cfgs = getattr(cfg, "task_configs", None) or {}
            for task_type, gen in self.GENERATORS.items():
                if task_type not in task_cfgs:
                    continue
                params = dict(task_cfgs.get(task_type) or {})
                for task in gen(self, cfg, params):
                    task.params.update(params)
                    # overlap (not just exact-set) dedupe: a superset of
                    # an in-flight task — a segment sealed mid-flight —
                    # must wait for the next tick, or its execution
                    # would re-process the in-flight inputs
                    if task.table not in busy:
                        busy[task.table] = self.queue.active_segments(
                            task.table)
                    if set(task.segments) & busy[task.table]:
                        continue
                    self.submit(task)
                    busy[task.table].update(task.segments)
                    n += 1
        return n

    def start(self, interval_s: Optional[float] = None) -> None:
        interval = interval_s if interval_s is not None else \
            self.config.get_float("pinot.controller.task.frequency.seconds")

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — periodic must survive
                    log.exception("task cadence tick failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="task-manager")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.queue.close()

    # -- queue facade (coordination ops call through here) -------------
    def submit(self, task: TaskConfig) -> TaskEntry:
        # cross-process trace propagation: a task submitted from a
        # traced context (admin op, future query-driven builds) carries
        # the TraceContext in its params; the leasing minion joins the
        # trace and ships its span tree back on completion
        from pinot_tpu.utils import tracing
        req = tracing.current_request()
        if req is not None and "traceContext" not in task.params:
            task.params["traceContext"] = req.wire_context()
        return self.queue.submit(task)

    def lease(self, worker: str,
              task_types: Optional[List[str]] = None) -> Optional[TaskEntry]:
        return self.queue.lease(worker, task_types)

    # -- the atomic swap -----------------------------------------------
    def segment_replace(self, task_id: str, adds: List[dict],
                        removes: List[Tuple[str, str]]) -> dict:
        """Commit a task's output: upsert `adds` (SegmentState dicts,
        dir_path already a durable deep-store URI or loadable path) and
        drop `removes` [(physical_table, name)] in ONE ClusterState
        mutation — a single watch notification, a single routing-epoch
        move. Instance placement: live servers via assign_balanced when
        any are registered, else the union of the removed segments'
        holders (embedded harnesses place through on_replace).

        Idempotent by construction: deterministic segment names make the
        replayed upsert a same-content overwrite and the replayed
        removes no-ops — plus a fast-path memo on task_id."""
        fire("controller.segment.replace", task_id=task_id)
        from pinot_tpu.controller.assignment import assign_balanced
        add_states = [SegmentState.from_dict(d) for d in adds]
        with self._replace_lock:
            if task_id and task_id in self._applied:
                return {"ok": True, "applied": False}
            removed_holders: List[str] = []
            for table, name in removes:
                st = self.state.segments.get(table, {}).get(name)
                if st is not None:
                    removed_holders.extend(st.instances)
            for st in add_states:
                if st.instances:
                    continue
                cfg = self.state.tables.get(st.table.rsplit("_", 1)[0])
                replication = cfg.retention.replication if cfg else 1
                if self.state.live_instances():
                    st.instances = assign_balanced(
                        self.state, st.table, st.name,
                        replication=replication)
                else:
                    st.instances = sorted(set(removed_holders))
            self.state.replace_segments(add_states, list(removes))
            if task_id:
                self._applied[task_id] = None
                while len(self._applied) > self._applied_max:
                    self._applied.popitem(last=False)
        if self.on_replace is not None:
            self.on_replace(add_states, list(removes))
        return {"ok": True, "applied": True}
