"""Minion-style background tasks: merge-rollup, realtime-to-offline, purge.

Reference parity: pinot-minion + pinot-controller minion/PinotTaskManager:84
— generators scan cluster state and emit task configs; executors run them
(ref TaskFactoryRegistry bridging the Helix Task Framework to
PinotTaskExecutor). Without Helix, tasks run on a local thread pool with
the same generate/execute split, so distributed workers can be added
behind the same interfaces.

MergeRollupTask: merge N small segments of a time bucket into one
(ref pinot-plugins minion-tasks merge-rollup).
RealtimeToOfflineTask: move completed realtime segments' rows into the
OFFLINE table (ref realtime-to-offline-segments task).
PurgeTask: rewrite segments dropping rows matching a predicate.
"""
from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from pinot_tpu.controller.cluster_state import ClusterState, SegmentState
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegment, load_segment


@dataclass
class TaskConfig:
    task_type: str
    table: str                      # physical table name
    segments: List[str]
    params: Dict[str, Any] = field(default_factory=dict)
    #: set by the TaskManager queue; folds into output segment names so a
    #: re-leased task rebuilds the SAME segments (idempotent commit)
    task_id: str = ""


def task_token(task: TaskConfig) -> str:
    """Deterministic output-name token for a task: a function of the
    task's INPUT identity only (never wall-clock or worker identity), so
    any re-execution — retry, re-lease after a crash — produces
    identically named segments and the segment-replace commit stays
    idempotent."""
    h = hashlib.sha1()
    for part in (task.task_type, task.table, *sorted(task.segments),
                 task.task_id):
        h.update(part.encode())
        h.update(b"\0")
    return h.hexdigest()[:10]


class TaskExecutor:
    """Ref PinotTaskExecutor."""
    task_type = ""

    def execute(self, task: TaskConfig, ctx: "TaskContext") -> Dict[str, Any]:
        raise NotImplementedError


@dataclass
class TaskContext:
    """Local (in-controller) execution context: segment mutations apply
    straight to ClusterState. Executors go through publish_segment /
    retire_segment / segment_state, never ctx.state directly — the
    minion worker substitutes a collecting context (minion/worker.py
    MinionTaskContext) that runs the SAME executors against a state
    snapshot and commits through the controller's atomic swap."""
    state: ClusterState
    output_dir: str
    task_id: str = ""

    def table_config(self, physical_table: str) -> TableConfig:
        base = physical_table.rsplit("_", 1)[0]
        return self.state.tables[base]

    def schema_for(self, physical_table: str) -> Schema:
        base = physical_table.rsplit("_", 1)[0]
        return self.state.schemas[base]

    def segment_state(self, table: str, name: str) -> SegmentState:
        return self.state.segments.get(table, {})[name]

    def publish_segment(self, st: SegmentState) -> None:
        self.state.upsert_segment(st)

    def retire_segment(self, table: str, name: str) -> None:
        self.state.remove_segment(table, name)

    def load(self, table: str, name: str) -> ImmutableSegment:
        import os

        from pinot_tpu.segment.fs import localize_segment
        st = self.segment_state(table, name)
        # deep-store URIs download into the task work area first
        local = localize_segment(
            st.dir_path, os.path.join(self.output_dir, "_downloads"))
        return load_segment(local)


def _segments_to_columns(segs: Sequence[ImmutableSegment],
                         schema: Schema) -> Dict[str, list]:
    cols: Dict[str, list] = {}
    for spec in schema.fields:
        if spec.virtual:
            continue
        parts = []
        for s in segs:
            if s.has_column(spec.name):
                vals = s.data_source(spec.name).values()
                parts.append(list(vals) if not isinstance(vals, list) else vals)
            else:
                parts.append([None] * s.num_docs)
        cols[spec.name] = [v for p in parts for v in p]
    return cols


class MergeRollupTaskExecutor(TaskExecutor):
    """Merge small segments; optional rollup aggregates duplicate dim rows
    (ref MergeRollupTask: CONCAT and ROLLUP merge types)."""
    task_type = "MergeRollupTask"

    def execute(self, task: TaskConfig, ctx: TaskContext) -> Dict[str, Any]:
        table = task.table
        cfg = ctx.table_config(table)
        schema = ctx.schema_for(table)
        segs = [ctx.load(table, n) for n in task.segments]
        columns = _segments_to_columns(segs, schema)
        if task.params.get("mergeType", "CONCAT").upper() == "ROLLUP":
            columns = _rollup(columns, schema)
        name = task.params.get(
            "segmentName", f"{cfg.name}_merged_{task_token(task)}")
        out_dir = os.path.join(ctx.output_dir, name)
        SegmentCreator(cfg, schema).build(columns, out_dir, name)
        merged = load_segment(out_dir)
        meta = merged.metadata
        ctx.publish_segment(SegmentState(
            name=name, table=table, instances=[], dir_path=out_dir,
            num_docs=meta.num_docs, start_time=meta.start_time,
            end_time=meta.end_time, crc=meta.crc))
        for old in task.segments:
            ctx.retire_segment(table, old)
        return {"mergedSegment": name, "numDocs": meta.num_docs,
                "replaced": task.segments}


def _rollup(columns: Dict[str, list], schema: Schema) -> Dict[str, list]:
    """Aggregate metric columns over identical dimension tuples."""
    from pinot_tpu.models import FieldType
    dim_names = [f.name for f in schema.fields
                 if f.field_type is not FieldType.METRIC and not f.virtual]
    met_names = [f.name for f in schema.fields
                 if f.field_type is FieldType.METRIC and not f.virtual]
    keys: Dict[tuple, int] = {}
    out: Dict[str, list] = {c: [] for c in columns}
    for i in range(len(next(iter(columns.values())))):
        key = tuple(columns[d][i] for d in dim_names)
        at = keys.get(key)
        if at is None:
            keys[key] = len(out[dim_names[0]]) if dim_names else i
            for c in columns:
                out[c].append(columns[c][i])
        else:
            for m in met_names:
                out[m][at] = out[m][at] + columns[m][i]
    return out


class RealtimeToOfflineTaskExecutor(TaskExecutor):
    """Move sealed realtime segments' rows into the OFFLINE table
    (ref RealtimeToOfflineSegmentsTask)."""
    task_type = "RealtimeToOfflineSegmentsTask"

    def execute(self, task: TaskConfig, ctx: TaskContext) -> Dict[str, Any]:
        rt_table = task.table
        base = rt_table.rsplit("_", 1)[0]
        off_table = f"{base}_OFFLINE"
        cfg = ctx.table_config(rt_table)
        schema = ctx.schema_for(rt_table)
        segs = [ctx.load(rt_table, n) for n in task.segments]
        columns = _segments_to_columns(segs, schema)
        name = f"{base}_r2o_{task_token(task)}"
        out_dir = os.path.join(ctx.output_dir, name)
        SegmentCreator(cfg, schema).build(columns, out_dir, name)
        merged = load_segment(out_dir)
        ctx.publish_segment(SegmentState(
            name=name, table=off_table, instances=[], dir_path=out_dir,
            num_docs=merged.num_docs,
            start_time=merged.metadata.start_time,
            end_time=merged.metadata.end_time, crc=merged.metadata.crc))
        for old in task.segments:
            ctx.retire_segment(rt_table, old)
        return {"offlineSegment": name, "numDocs": merged.num_docs}


class PurgeTaskExecutor(TaskExecutor):
    """Rewrite segments dropping rows the purge predicate matches
    (ref PurgeTask with a RecordPurger). Segments with NO matching rows
    are rewritten too (same data, ``_purged`` name): the suffix is the
    generator's only convergence marker, so a skipped no-match segment
    would be rescanned — and its filter re-evaluated — on every cadence
    tick forever."""
    task_type = "PurgeTask"

    def execute(self, task: TaskConfig, ctx: TaskContext) -> Dict[str, Any]:
        from pinot_tpu.ingest.transforms import parse_expression
        from pinot_tpu.query.filter import evaluate_filter
        table = task.table
        cfg = ctx.table_config(table)
        schema = ctx.schema_for(table)
        predicate = parse_expression(task.params["purgePredicate"])
        purged = []
        for seg_name in task.segments:
            seg = ctx.load(table, seg_name)
            drop = evaluate_filter(seg, predicate)
            keep = ~drop
            columns = {}
            for spec in schema.fields:
                if spec.virtual:
                    continue
                vals = np.asarray(seg.data_source(spec.name).values())
                columns[spec.name] = vals[keep]
            name = f"{seg_name}_purged"
            out_dir = os.path.join(ctx.output_dir, name)
            SegmentCreator(cfg, schema).build(columns, out_dir, name)
            m = load_segment(out_dir).metadata
            old_state = ctx.segment_state(table, seg_name)
            ctx.publish_segment(SegmentState(
                name=name, table=table, instances=list(old_state.instances),
                dir_path=out_dir, num_docs=m.num_docs,
                start_time=m.start_time, end_time=m.end_time, crc=m.crc))
            ctx.retire_segment(table, seg_name)
            purged.append(name)
        return {"purgedSegments": purged}


class StarTreeBuildTaskExecutor(TaskExecutor):
    """Grow star-trees on already-sealed segments WITHOUT re-ingest:
    rebuild each segment from its own columns under a config carrying
    starTreeIndexConfigs, and commit through the same publish/retire
    (manifest + replace_segments) swap as every other rewrite task.
    This is how a realtime table whose seal path skipped tree building
    (or whose tree config was added after the fact) converges onto the
    device star-tree serving path, one routing-epoch swap per segment.

    The tree config comes from task params ("starTreeIndexConfigs",
    list of StarTreeIndexConfig dicts) or, absent that, the table's
    indexing config. Build output is deterministic in the input segment
    bytes + config (the builder has no randomness and the output name
    is a pure function of the input name), so a re-leased crashed task
    rebuilds byte-identical trees and the commit stays idempotent.
    Convergence marker is the segment metadata's "starTree" entry — not
    a name suffix — so the generator never rescans a built segment."""
    task_type = "StarTreeBuildTask"

    def execute(self, task: TaskConfig, ctx: TaskContext) -> Dict[str, Any]:
        import copy

        from pinot_tpu.models import StarTreeIndexConfig
        from pinot_tpu.utils.failpoints import fire
        table = task.table
        cfg = ctx.table_config(table)
        if cfg.upsert:
            raise ValueError(
                "StarTreeBuildTask on upsert table: pre-aggregated "
                "records cannot apply validDocIds")
        schema = ctx.schema_for(table)
        st_cfgs = [StarTreeIndexConfig.from_dict(d)
                   for d in task.params.get("starTreeIndexConfigs") or []]
        if not st_cfgs:
            st_cfgs = list(cfg.indexing.star_tree_configs)
        if not st_cfgs:
            raise ValueError(
                "StarTreeBuildTask needs starTreeIndexConfigs (task "
                "params or table indexing config)")
        build_cfg = copy.deepcopy(cfg)
        build_cfg.indexing.star_tree_configs = st_cfgs
        built = []
        for seg_name in task.segments:
            # chaos site: a crash here leaves the source segment
            # serving via the scan path; the re-leased task rebuilds
            # the SAME tree bytes (deterministic build + output name)
            fire("minion.startree.build", table=table, segment=seg_name)
            seg = ctx.load(table, seg_name)
            columns = {}
            for spec in schema.fields:
                if spec.virtual:
                    continue
                columns[spec.name] = np.asarray(
                    seg.data_source(spec.name).values())
            name = f"{seg_name}_sttree"
            out_dir = os.path.join(ctx.output_dir, name)
            SegmentCreator(build_cfg, schema).build(columns, out_dir, name)
            m = load_segment(out_dir).metadata
            old_state = ctx.segment_state(table, seg_name)
            ctx.publish_segment(SegmentState(
                name=name, table=table,
                instances=list(old_state.instances), dir_path=out_dir,
                num_docs=m.num_docs, start_time=m.start_time,
                end_time=m.end_time, crc=m.crc))
            ctx.retire_segment(table, seg_name)
            built.append(name)
        return {"builtSegments": built}


class ClpCompactionTaskExecutor(TaskExecutor):
    """Re-encode sealed log segments into CLP forward-index form (the
    y-scope fork's compaction of realtime text columns into
    CLPForwardIndexCreatorV2 segments): rebuild each segment from its
    own columns under a config whose indexing.clp_columns carries the
    log columns, and commit through the same publish/retire (manifest +
    replace_segments) swap as every other rewrite task. Once swapped,
    LIKE/regex over the log column serves from the device pushdown leg
    (ops/clp_device.py) instead of host-side full decode.

    The column list comes from task params ("clpColumns") or, absent
    that, the table's indexing config. The rebuild is deterministic in
    the input segment bytes + config (encode_message has no randomness;
    the output name is a pure function of the input name), so a
    re-leased crashed task rebuilds byte-identical segments and the
    commit stays idempotent. Convergence marker is it.CLP in the column
    metadata's index list — not a name suffix — so the generator never
    rescans a compacted segment."""
    task_type = "ClpCompactionTask"

    def execute(self, task: TaskConfig, ctx: TaskContext) -> Dict[str, Any]:
        import copy

        from pinot_tpu.utils.failpoints import fire
        table = task.table
        cfg = ctx.table_config(table)
        schema = ctx.schema_for(table)
        clp_cols = list(task.params.get("clpColumns") or
                        cfg.indexing.clp_columns)
        if not clp_cols:
            raise ValueError(
                "ClpCompactionTask needs clpColumns (task params or "
                "table indexing config)")
        build_cfg = copy.deepcopy(cfg)
        build_cfg.indexing.clp_columns = clp_cols
        compacted = []
        for seg_name in task.segments:
            # chaos site: a crash here leaves the source segment
            # serving via the host decode path; the re-leased task
            # re-encodes the SAME bytes (deterministic codec + name)
            fire("minion.clp.compact", table=table, segment=seg_name)
            seg = ctx.load(table, seg_name)
            columns = {}
            for spec in schema.fields:
                if spec.virtual:
                    continue
                columns[spec.name] = np.asarray(
                    seg.data_source(spec.name).values())
            name = f"{seg_name}_clp"
            out_dir = os.path.join(ctx.output_dir, name)
            SegmentCreator(build_cfg, schema).build(columns, out_dir, name)
            m = load_segment(out_dir).metadata
            old_state = ctx.segment_state(table, seg_name)
            ctx.publish_segment(SegmentState(
                name=name, table=table,
                instances=list(old_state.instances), dir_path=out_dir,
                num_docs=m.num_docs, start_time=m.start_time,
                end_time=m.end_time, crc=m.crc))
            ctx.retire_segment(table, seg_name)
            compacted.append(name)
        return {"compactedSegments": compacted, "clpColumns": clp_cols}


# -- generators (ref PinotTaskGenerator) ------------------------------------

def generate_merge_rollup_tasks(state: ClusterState, table: str,
                                max_docs_per_merged: int = 5_000_000,
                                min_segments: int = 2) -> List[TaskConfig]:
    """Group small ONLINE segments into merge buckets."""
    segs = sorted((s for s in state.table_segments(table)
                   if s.status == "ONLINE"),
                  key=lambda s: (s.start_time or 0, s.name))
    tasks: List[TaskConfig] = []
    bucket: List[SegmentState] = []
    docs = 0
    for s in segs:
        if docs + s.num_docs > max_docs_per_merged and len(bucket) >= min_segments:
            tasks.append(TaskConfig("MergeRollupTask", table,
                                    [b.name for b in bucket]))
            bucket, docs = [], 0
        bucket.append(s)
        docs += s.num_docs
    if len(bucket) >= min_segments:
        tasks.append(TaskConfig("MergeRollupTask", table,
                                [b.name for b in bucket]))
    return tasks


def generate_realtime_to_offline_tasks(
        state: ClusterState, table_base: str,
        max_segments_per_task: int = 16,
        min_segments: int = 1) -> List[TaskConfig]:
    """Batch SEALED (ONLINE) realtime segments into move tasks (ref
    RealtimeToOfflineSegmentsTaskGenerator): CONSUMING segments are
    still being written and never move; completed ones migrate to the
    OFFLINE table in start-time order. Once a task commits, its inputs
    are retired from the realtime table, so the scan self-quiesces."""
    rt = f"{table_base}_REALTIME"
    segs = sorted((s for s in state.table_segments(rt)
                   if s.status == "ONLINE"),
                  key=lambda s: (s.start_time or 0, s.name))
    tasks: List[TaskConfig] = []
    for i in range(0, len(segs), max_segments_per_task):
        chunk = segs[i:i + max_segments_per_task]
        if len(chunk) >= min_segments:
            tasks.append(TaskConfig("RealtimeToOfflineSegmentsTask", rt,
                                    [c.name for c in chunk]))
    return tasks


def generate_purge_tasks(state: ClusterState, table: str,
                         max_segments_per_task: int = 16
                         ) -> List[TaskConfig]:
    """Batch ONLINE segments into purge-rewrite tasks (ref
    PurgeTaskGenerator). The executor's deterministic ``_purged`` output
    suffix marks a segment as already rewritten under the table's
    predicate (no-match segments rewrite too — see the executor), so
    rescans skip it and the generator converges instead of purging its
    own output forever. The purgePredicate itself rides in from
    TableConfig.task_configs via the TaskManager scan. Known limits of
    the name-suffix marker (a metadata flag would fix both): it means
    "rewritten under SOME predicate" — after changing a table's
    purgePredicate, already-``_purged`` segments are not rescanned
    (submit explicit PurgeTasks via REST ``POST /tasks`` to apply a new
    predicate to old outputs) — and other executors' outputs drop it, so
    on a table also running merge-rollup each merged segment pays one
    extra (usually no-match) rewrite before it re-converges."""
    segs = sorted((s for s in state.table_segments(table)
                   if s.status == "ONLINE"
                   and not s.name.endswith("_purged")),
                  key=lambda s: s.name)
    tasks: List[TaskConfig] = []
    for i in range(0, len(segs), max_segments_per_task):
        chunk = segs[i:i + max_segments_per_task]
        tasks.append(TaskConfig("PurgeTask", table,
                                [c.name for c in chunk]))
    return tasks


def generate_startree_build_tasks(state: ClusterState, table: str,
                                  max_segments_per_task: int = 16
                                  ) -> List[TaskConfig]:
    """Batch ONLINE segments that carry NO star-tree into build tasks.
    The convergence marker is the segment metadata's "starTree" entry
    (one json peek per candidate — no segment load), so the scan
    self-quiesces after one pass instead of rebuilding its own output;
    segments whose metadata isn't locally readable (deep-store URIs not
    yet localized) are skipped this tick rather than churned."""
    import json

    def has_tree(s: SegmentState) -> bool:
        try:
            with open(os.path.join(s.dir_path, "metadata.json")) as f:
                return bool(json.load(f).get("starTree"))
        except (OSError, ValueError):
            return True  # unreadable here -> leave it alone
    segs = sorted((s for s in state.table_segments(table)
                   if s.status == "ONLINE" and not has_tree(s)),
                  key=lambda s: s.name)
    tasks: List[TaskConfig] = []
    for i in range(0, len(segs), max_segments_per_task):
        chunk = segs[i:i + max_segments_per_task]
        tasks.append(TaskConfig("StarTreeBuildTask", table,
                                [c.name for c in chunk]))
    return tasks


def generate_clp_compaction_tasks(state: ClusterState, table: str,
                                  max_segments_per_task: int = 16
                                  ) -> List[TaskConfig]:
    """Batch ONLINE segments whose configured CLP columns are NOT yet
    CLP-encoded into compaction tasks. Convergence marker: it.CLP in
    the column's metadata index list (one json peek per candidate — no
    segment load), so the scan self-quiesces after one pass; segments
    whose metadata isn't locally readable (deep-store URIs not yet
    localized) are skipped this tick rather than churned."""
    import json

    from pinot_tpu.segment import index_types as it
    base = table.rsplit("_", 1)[0]
    cfg = state.tables.get(base)
    clp_cols = list(getattr(cfg.indexing, "clp_columns", None) or []) \
        if cfg is not None else []
    if not clp_cols:
        return []

    def compacted(s: SegmentState) -> bool:
        try:
            with open(os.path.join(s.dir_path, "metadata.json")) as f:
                cols = json.load(f).get("columns", {})
        except (OSError, ValueError):
            return True  # unreadable here -> leave it alone
        for c in clp_cols:
            cm = cols.get(c)
            if cm is not None and it.CLP not in cm.get("indexes", []):
                return False
        return True
    segs = sorted((s for s in state.table_segments(table)
                   if s.status == "ONLINE" and not compacted(s)),
                  key=lambda s: s.name)
    tasks: List[TaskConfig] = []
    for i in range(0, len(segs), max_segments_per_task):
        chunk = segs[i:i + max_segments_per_task]
        tasks.append(TaskConfig("ClpCompactionTask", table,
                                [c.name for c in chunk]))
    return tasks


_EXECUTORS: Dict[str, TaskExecutor] = {}


def register_executor(ex: TaskExecutor) -> None:
    _EXECUTORS[ex.task_type] = ex


def registered_task_types() -> List[str]:
    """Task types with a registered executor — a worker that declared no
    explicit types leases exactly these (and can meter per-type
    concurrency against the full list)."""
    return sorted(_EXECUTORS)


def run_task(task: TaskConfig, ctx: TaskContext) -> Dict[str, Any]:
    """Ref TaskFactoryRegistry.executeTask."""
    ex = _EXECUTORS.get(task.task_type)
    if ex is None:
        raise ValueError(f"no executor for task type {task.task_type!r}")
    return ex.execute(task, ctx)


register_executor(MergeRollupTaskExecutor())
register_executor(RealtimeToOfflineTaskExecutor())
register_executor(PurgeTaskExecutor())
register_executor(StarTreeBuildTaskExecutor())
register_executor(ClpCompactionTaskExecutor())
