"""Fleet health plane (PR 14): metrics history, cluster rollup,
workload accounting, SLO burn-rate watchdog.

Four layers, bottom up:

* ``history`` — a bounded per-role ring of timestamped
  ``MetricsRegistry.sample()`` snapshots, filled by a background
  sampler thread; ``/debug/metrics/history`` serves it on every role,
  and ``timeseries/engine.py`` can query it (selfmetrics — the
  time-series engine's first real consumer).
* ``workload`` — per-(tenant, table, plan-fingerprint) cost rollup fed
  from ``utils/accounting.QueryUsage`` at query finish; top-K by cost
  at ``/debug/workload``.
* ``slo`` — declarative targets (``pinot.slo.*``) evaluated as
  multi-window burn rates over the history: ``slo_burn_rate`` gauges, a
  structured ``SLO_BREACH`` log line, and a degraded verdict.
* ``rollup`` — the controller's cluster-wide sweep: scrape every live
  instance's ``/debug/health`` + ``/debug/metrics/sample`` into
  ``GET /cluster/metrics`` and ``GET /cluster/health``.
"""
from pinot_tpu.health.history import (  # noqa: F401
    MetricsHistory, MetricsSampler, get_history, start_sampling,
    stop_sampling)
from pinot_tpu.health.workload import WorkloadRegistry, get_workload  # noqa: F401,E501
from pinot_tpu.health.slo import SloWatchdog, get_watchdog  # noqa: F401
from pinot_tpu.health.rollup import (  # noqa: F401
    ClusterHealthMonitor, ScrapeTarget, make_cluster_monitor,
    role_health_summary)
from pinot_tpu.health.selfmetrics import query_history  # noqa: F401
