"""Brownout mode: the degradation ladder closing the SLO observe->act loop.

PR 14 gave every role eyes — metrics history, the multi-window SLO
burn-rate watchdog — but no hands: a breached SLO paged a human. This
module is the actuator ("brownout": trade optional quality for capacity,
Klein et al., ICSE 2014; DAGOR's cooperative degradation, SOSP 2018).
Per role, a :class:`BrownoutController` runs as a metrics-sampler hook
beside the watchdog and walks a four-rung ladder, cheapest sacrifice
first:

====  ================  ====================================================
rung  name              effect while engaged (level >= rung)
====  ================  ====================================================
1     hedge_off         hedged scatter auto-disables (broker) — speculative
                        duplicate load is the first thing to stop
2     stale_cache       the broker result cache may serve entries up to
                        ``pinot.brownout.stale.ttl.grace.seconds`` past
                        TTL, flagged ``staleResult=true`` — stale beats
                        shed for dashboard traffic
3     batch_shrink      dispatch-ring batch windows shrink by
                        ``pinot.brownout.batch.window.scale`` (server) —
                        trade coalescing efficiency for queue latency
4     shed_secondary    admission rejects secondary workloads whole
                        (server) — primary traffic gets every thread
====  ================  ====================================================

Climb signal (either suffices): the role's SLO watchdog reports a
sustained multi-window breach, OR the shed rate — admission rejections
plus overload partials per query over the short history window — is at/
over ``pinot.brownout.shed.rate.threshold``. Hysteresis both ways: one
rung UP only after the signal has held ``pinot.brownout.up.seconds``
since the last transition; one rung DOWN only after it has stayed clear
(below HALF the entry threshold, and the watchdog quiet) for
``pinot.brownout.down.seconds``. Transitions are logged onset-only
(one ``BROWNOUT_TRANSITION`` JSON line per rung move, not per tick),
metered (``brownout_transitions{direction=}``), gauged
(``brownout_level``), and served in ``/debug/health`` (and therefore
``/cluster/health``) via :func:`payload`.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, Optional

from pinot_tpu.utils.metrics import get_registry

brownout_log = logging.getLogger("pinot_tpu.brownout")

#: the ladder, cheapest sacrifice first; level N = rungs 1..N engaged
RUNGS = ("hedge_off", "stale_cache", "batch_shrink", "shed_secondary")

#: counter families in the shed-rate numerator / denominator, per role
_SHED_FAMILIES = ("server_admission_rejected", "broker_overload_partials")
_QUERY_FAMILIES = ("broker_queries", "queries")


class BrownoutController:
    """Walks the ladder for ONE role over that role's history +
    watchdog. ``evaluate`` is the sampler hook; ``now`` is injectable
    so hysteresis unit tests need no real sleeps."""

    def __init__(self, role: str, history, config=None, watchdog=None,
                 metrics=None):
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = config or PinotConfiguration()
        self.role = role
        self.history = history
        self._watchdog = watchdog
        self._metrics = metrics if metrics is not None \
            else get_registry(role)
        self.enabled = cfg.get_bool("pinot.brownout.enabled", True)
        self.shed_threshold = max(1e-6, cfg.get_float(
            "pinot.brownout.shed.rate.threshold"))
        self.up_s = max(0.0, cfg.get_float("pinot.brownout.up.seconds"))
        self.down_s = max(0.0, cfg.get_float(
            "pinot.brownout.down.seconds"))
        self.window_s = max(1.0, cfg.get_float(
            "pinot.slo.window.short.seconds"))
        self.batch_window_scale = min(1.0, max(0.0, cfg.get_float(
            "pinot.brownout.batch.window.scale")))
        self.stale_grace_s = max(0.0, cfg.get_float(
            "pinot.brownout.stale.ttl.grace.seconds"))
        self._lock = threading.Lock()
        self._level = 0
        self._signal_since: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._last_change = 0.0
        self._last_shed_rate = 0.0
        self._last_signal = False

    # -- signal ---------------------------------------------------------
    def _shed_rate(self, now: float) -> float:
        shed = sum(self.history.counter_sum_delta(f, self.window_s,
                                                  now=now)[0]
                   for f in _SHED_FAMILIES)
        queries = sum(self.history.counter_sum_delta(f, self.window_s,
                                                     now=now)[0]
                      for f in _QUERY_FAMILIES)
        if queries <= 0:
            return 0.0
        return shed / queries

    def _signal_locked(self, now: float) -> bool:
        """True = degrade. Entry threshold for the shed rate; the
        watchdog's own multi-window logic is its debounce."""
        if self._watchdog is not None and self._watchdog.breached():
            return True
        return self._last_shed_rate >= self.shed_threshold

    def _clear_locked(self, now: float) -> bool:
        """True = recovery evidence. HALF the entry threshold (classic
        hysteresis: the exit bar is lower than the entry bar, so a
        shed rate hovering at the threshold cannot flap the ladder)."""
        if self._watchdog is not None and self._watchdog.breached():
            return False
        return self._last_shed_rate < 0.5 * self.shed_threshold

    # -- evaluation (sampler hook) --------------------------------------
    def evaluate(self, now: Optional[float] = None) -> int:
        if not self.enabled:
            return 0
        now = now if now is not None else time.time()
        shed_rate = self._shed_rate(now)
        with self._lock:
            self._last_shed_rate = shed_rate
            sig = self._signal_locked(now)
            clear = self._clear_locked(now)
            self._last_signal = sig
            if sig:
                self._clear_since = None
                if self._signal_since is None:
                    self._signal_since = now
                if self._level < len(RUNGS) \
                        and now - self._signal_since >= self.up_s \
                        and now - self._last_change >= self.up_s:
                    self._move_locked(+1, now, shed_rate)
            elif clear:
                self._signal_since = None
                if self._clear_since is None:
                    self._clear_since = now
                if self._level > 0 \
                        and now - self._clear_since >= self.down_s \
                        and now - self._last_change >= self.down_s:
                    self._move_locked(-1, now, shed_rate)
            else:
                # between the exit and entry thresholds: hold the rung,
                # reset both hysteresis clocks
                self._signal_since = None
                self._clear_since = None
            level = self._level
        self._metrics.set_gauge("brownout_level", level)
        return level

    def _move_locked(self, step: int, now: float,
                     shed_rate: float) -> None:
        self._level += step
        self._last_change = now
        # re-arm the hysteresis clocks so multi-rung moves each take a
        # full sustain period
        self._signal_since = now if step > 0 else None
        self._clear_since = now if step < 0 else None
        direction = "up" if step > 0 else "down"
        self._metrics.add_meter("brownout_transitions",
                                labels={"direction": direction})
        brownout_log.warning("BROWNOUT_TRANSITION %s", json.dumps({
            "role": self.role, "direction": direction,
            "level": self._level,
            "rung": RUNGS[self._level - 1] if self._level else None,
            "shedRate": round(shed_rate, 4),
            "sloBreached": bool(self._watchdog is not None
                                and self._watchdog.breached())},
            default=str))

    # -- read side ------------------------------------------------------
    def level(self) -> int:
        with self._lock:
            return self._level

    def engaged(self, rung: str) -> bool:
        idx = RUNGS.index(rung) + 1
        with self._lock:
            return self.enabled and self._level >= idx

    def payload(self) -> dict:
        """The /debug/health brownout subsystem verdict."""
        with self._lock:
            level = self._level
            shed = self._last_shed_rate
            sig = self._last_signal
        return {
            "ok": level == 0,
            "level": level,
            "rung": RUNGS[level - 1] if level else None,
            "engaged": list(RUNGS[:level]),
            "shedRate": round(shed, 4),
            "signal": sig,
        }


# -- per-role singletons (populated by history.start_sampling) ---------------
_controllers: Dict[str, BrownoutController] = {}
_lock = threading.Lock()


def get_brownout(role: str = "server") -> Optional[BrownoutController]:
    with _lock:
        return _controllers.get(role)


def _register_brownout(role: str,
                       ctrl: Optional[BrownoutController]) -> None:
    with _lock:
        if ctrl is None:
            _controllers.pop(role, None)
        else:
            _controllers[role] = ctrl


def engaged(role: str, rung: str) -> bool:
    """Actuation predicate the hot paths call: False when no controller
    is registered (no sampler running) or the rung is above the current
    level — so with brownout absent everything behaves exactly as
    before."""
    ctrl = get_brownout(role)
    return ctrl is not None and ctrl.engaged(rung)


def window_scale(role: str = "server") -> float:
    """Dispatch batch-window multiplier: 1.0 normally, the configured
    shrink factor while the ``batch_shrink`` rung is engaged."""
    ctrl = get_brownout(role)
    if ctrl is None or not ctrl.engaged("batch_shrink"):
        return 1.0
    return ctrl.batch_window_scale
