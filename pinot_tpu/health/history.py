"""Per-role metrics history: a bounded ring of registry samples.

Reference parity: the controller's periodic health tasks over the typed
role registries (pinot-controller periodictask/ — e.g.
SegmentStatusChecker sampling cluster metrics on a cadence). Here each
role keeps its OWN recent history in memory: a background
:class:`MetricsSampler` appends one ``MetricsRegistry.sample()``
snapshot per ``pinot.metrics.history.interval.ms``, the ring holds
``pinot.metrics.history.window.seconds`` worth, ``/debug/metrics/
history`` serves it raw, the SLO watchdog evaluates burn rates over it,
and ``health/selfmetrics.py`` exposes it as a table the time-series
engine can query.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from pinot_tpu.utils.metrics import get_registry


def family_items(mapping: Dict[str, float], family: str):
    """(flat name, value) pairs of one metric family across its label
    sets: a flat sample key matches when it IS the family name or
    starts with ``family{``. THE series-identity rule every health
    consumer shares — if MetricsRegistry.sample key formatting ever
    changes, this is the one predicate to update."""
    for k, v in mapping.items():
        if k == family or k.startswith(family + "{"):
            yield k, v


class MetricsHistory:
    """Bounded FIFO of flat registry samples for one role."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(2, int(capacity))
        self._samples: deque = deque()
        self._lock = threading.Lock()

    def append(self, sample: dict) -> None:
        with self._lock:
            self._samples.append(sample)
            while len(self._samples) > self.capacity:
                self._samples.popleft()

    def samples(self, window_s: Optional[float] = None,
                now: Optional[float] = None) -> List[dict]:
        """Oldest-first samples; window_s restricts to the trailing
        window (sample ts >= now - window_s)."""
        with self._lock:
            out = list(self._samples)
        if window_s is None:
            return out
        cutoff = (now if now is not None else time.time()) - window_s
        return [s for s in out if s.get("ts", 0.0) >= cutoff]

    def latest(self) -> Optional[dict]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def counter_delta(self, flat_name: str, window_s: float,
                      now: Optional[float] = None
                      ) -> Tuple[float, float]:
        """(value delta, elapsed seconds) between the oldest and newest
        sample in the window for one flat counter series. A negative
        delta (registry reset between samples) clamps to the newest
        value — a restart must not read as negative traffic."""
        win = self.samples(window_s, now=now)
        if len(win) < 2:
            return 0.0, 0.0
        first, last = win[0], win[-1]
        v0 = float(first.get("counters", {}).get(flat_name, 0.0))
        v1 = float(last.get("counters", {}).get(flat_name, 0.0))
        delta = v1 - v0
        if delta < 0:
            delta = v1
        return delta, max(0.0, float(last["ts"]) - float(first["ts"]))

    def counter_sum_delta(self, name_prefix: str, window_s: float,
                          now: Optional[float] = None
                          ) -> Tuple[float, float]:
        """Like counter_delta but summed over every series whose flat
        name is ``name_prefix`` or starts with ``name_prefix{`` (all
        label sets of one family)."""
        win = self.samples(window_s, now=now)
        if len(win) < 2:
            return 0.0, 0.0

        def fam_total(sample: dict) -> float:
            return sum(float(v) for _k, v in family_items(
                sample.get("counters", {}), name_prefix))

        first, last = win[0], win[-1]
        delta = fam_total(last) - fam_total(first)
        if delta < 0:
            delta = fam_total(last)
        return delta, max(0.0, float(last["ts"]) - float(first["ts"]))

    def timer_series(self, name_prefix: str, field: str,
                     window_s: float, now: Optional[float] = None
                     ) -> List[Tuple[float, float]]:
        """(ts, value) per sample in the window for one timer family
        field (p99/p50/...), taking the WORST (max) value across label
        sets — the conservative fleet view of a latency quantile."""
        out: List[Tuple[float, float]] = []
        for s in self.samples(window_s, now=now):
            best: Optional[float] = None
            for _k, t in family_items(s.get("timers", {}), name_prefix):
                v = float(t.get(field, 0.0))
                if best is None or v > best:
                    best = v
            if best is not None:
                out.append((float(s["ts"]), best))
        return out

    def gauge_max(self, name_prefix: str) -> Optional[float]:
        """Max over label sets of one gauge family in the LATEST sample
        (e.g. worst ingestion_delay_ms across partitions)."""
        last = self.latest()
        if last is None:
            return None
        best: Optional[float] = None
        for _k, v in family_items(last.get("gauges", {}), name_prefix):
            if best is None or float(v) > best:
                best = float(v)
        return best

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()


class MetricsSampler:
    """Background thread appending one registry sample per interval to
    the role's history, then running registered hooks (the SLO watchdog
    evaluates there). ``sample_once()`` is the synchronous unit tests
    and the rollup drive directly."""

    def __init__(self, role: str, interval_s: float = 1.0,
                 history: Optional[MetricsHistory] = None,
                 registry=None):
        self.role = role
        self.interval_s = max(0.01, float(interval_s))
        self.history = history if history is not None else get_history(role)
        self._registry = registry if registry is not None \
            else get_registry(role)
        self._hooks: List[Callable[[], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_hook(self, fn: Callable[[], None]) -> None:
        self._hooks.append(fn)

    def sample_once(self) -> dict:
        sample = self._registry.sample()
        self.history.append(sample)
        self._registry.add_meter("metrics_history_samples")
        for fn in list(self._hooks):
            try:
                fn()
            except Exception:  # noqa: BLE001 — a hook bug must not
                # stop the sampling cadence (history feeds /cluster/health;
                # losing it would blind the fleet exactly when it's sick)
                import logging
                logging.getLogger(__name__).exception(
                    "metrics-sampler hook failed (role=%s)", self.role)
        return sample

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"metrics-sampler-{self.role}")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


# -- per-role singletons (the get_registry pattern) -------------------------
_histories: Dict[str, MetricsHistory] = {}
_samplers: Dict[str, MetricsSampler] = {}
_lock = threading.Lock()


def get_history(role: str = "server",
                capacity: Optional[int] = None) -> MetricsHistory:
    with _lock:
        h = _histories.get(role)
        if h is None:
            h = _histories[role] = MetricsHistory(capacity or 512)
        elif capacity is not None:
            h.capacity = max(2, int(capacity))
        return h


def start_sampling(role: str, config=None) -> Optional[MetricsSampler]:
    """Idempotently start the role's background sampler (plus its SLO
    watchdog hook) from config knobs. Returns None when
    ``pinot.metrics.history.enabled`` is off — the bench's A-side runs
    with NO history machinery at all."""
    from pinot_tpu.utils.config import PinotConfiguration
    cfg = config or PinotConfiguration()
    if not cfg.get_bool("pinot.metrics.history.enabled", True):
        return None
    interval_s = max(0.01, cfg.get_float(
        "pinot.metrics.history.interval.ms", 1000.0) / 1000.0)
    window_s = max(interval_s, cfg.get_float(
        "pinot.metrics.history.window.seconds", 300.0))
    capacity = max(8, int(window_s / interval_s) + 1)
    # resolve the history BEFORE taking the module lock — get_history
    # takes the same (non-reentrant) lock
    history = get_history(role, capacity=capacity)
    with _lock:
        existing = _samplers.get(role)
        if existing is not None:
            return existing
        sampler = MetricsSampler(role, interval_s=interval_s,
                                 history=history)
        _samplers[role] = sampler
    from pinot_tpu.health.slo import SloWatchdog, _register_watchdog
    dog = SloWatchdog(role, sampler.history, config=cfg)
    _register_watchdog(role, dog)
    sampler.add_hook(dog.evaluate)
    # the SLO observe->act loop: the brownout ladder evaluates AFTER
    # the watchdog each tick, so it acts on this tick's verdicts
    if cfg.get_bool("pinot.brownout.enabled", True):
        from pinot_tpu.health.brownout import (BrownoutController,
                                               _register_brownout)
        ctrl = BrownoutController(role, sampler.history, config=cfg,
                                  watchdog=dog)
        _register_brownout(role, ctrl)
        sampler.add_hook(ctrl.evaluate)
    sampler.start()
    return sampler


def stop_sampling(role: str) -> None:
    with _lock:
        sampler = _samplers.pop(role, None)
    if sampler is not None:
        sampler.stop()
    from pinot_tpu.health.brownout import _register_brownout
    from pinot_tpu.health.slo import _register_watchdog
    _register_watchdog(role, None)
    _register_brownout(role, None)
