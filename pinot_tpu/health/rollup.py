"""Cluster-wide health rollup: the controller's periodic fleet sweep.

Reference parity: pinot-controller periodictask/ (SegmentStatusChecker
and friends sampling cluster health on a cadence) over the typed role
registries. Here a :class:`ClusterHealthMonitor` periodically scrapes
every instance's ``/debug/health`` + ``/debug/metrics/sample`` (the
per-role admin surface every role mounts) and folds the results —
together with coordination-heartbeat liveness — into:

* ``GET /cluster/health`` — one JSON verdict per instance and
  subsystem: liveness, circuit-breaker states, ingestion lag /
  backpressure, task-queue depth, deadline-miss (errorCode-250) rates,
  SLO burn verdicts. A scrape failure marks the instance DEGRADED with
  the reason attached; the sweep itself never throws.
* ``GET /cluster/metrics`` — summed counters across instances (one
  fleet-wide number per family+labels) plus per-instance gauges.

The per-instance half lives in :func:`role_health_summary`: the local
verdict a role serves at ``/debug/health``, built from its latest
registry sample, its history, and its SLO watchdog.
"""
from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from pinot_tpu.health.history import family_items as _family_items
from pinot_tpu.utils.metrics import get_registry

log = logging.getLogger(__name__)

#: remote-tier circuit breaker gauge values (cache/remote.py)
_BREAKER_CLOSED = 0.0


def role_health_summary(role: str, config=None,
                        registry=None) -> dict:
    """The per-role /debug/health payload: a live/degraded verdict per
    subsystem from the role's OWN metrics + SLO watchdog. Cheap enough
    for every scrape tick — one registry sample, no history walk."""
    from pinot_tpu.health.history import get_history
    from pinot_tpu.health.slo import get_watchdog
    reg = registry if registry is not None else get_registry(role)
    sample = reg.sample()
    gauges = sample.get("gauges", {})
    counters = sample.get("counters", {})
    subsystems: Dict[str, dict] = {}

    # circuit breakers (remote cache tiers): any non-closed breaker is a
    # degraded data path — queries still serve, L2 is dark for its range
    breakers = {k: v for k, v in _family_items(
        gauges, "remote_cache_breaker_state")}
    open_breakers = {k: v for k, v in breakers.items()
                     if v != _BREAKER_CLOSED}
    subsystems["breakers"] = {
        "ok": not open_breakers,
        "open": sorted(open_breakers),
        "total": len(breakers)}

    # ingestion: worst per-partition lag + backpressure pause pressure
    lags = [v for _k, v in _family_items(gauges, "ingestion_delay_ms")]
    paused = [v for _k, v in _family_items(
        gauges, "ingest_consumer_paused")]
    subsystems["ingestion"] = {
        "ok": not any(paused),
        "maxDelayMs": round(max(lags), 3) if lags else None,
        "pausedPartitions": int(sum(1 for p in paused if p))}

    # task fabric: queue depth + worker occupancy (report-only — a deep
    # queue is load, not sickness; lease expiry handles stuck workers)
    depth = gauges.get("task_queue_depth")
    subsystems["tasks"] = {"ok": True, "queueDepth": depth}

    # HBM plane (report-only): pooled device-tier bytes plus, on a
    # multi-chip mesh, the per-chip split — admission sheds on the
    # MOST-loaded chip, so the max/total pair is what an operator needs
    # to see a skewed mesh before it starts rejecting
    cache_items = list(_family_items(gauges, "hbm_cache_bytes"))
    if cache_items:
        def _device_of(key: str) -> Optional[str]:
            m = re.search(r'device="([^"]*)"', key)
            return m.group(1) if m else None

        per_device = {_device_of(k): v for k, v in cache_items
                      if _device_of(k) is not None}
        pooled = [v for k, v in cache_items if _device_of(k) is None]
        resident = {d: v for d, v in
                    ((_device_of(k), v) for k, v in _family_items(
                        gauges, "hbm_resident_bytes"))
                    if d is not None}
        hbm: dict = {"ok": True,
                     "totalBytes": int(sum(pooled)) if pooled else
                     int(sum(per_device.values()))}
        if per_device:
            worst = max(per_device, key=per_device.get)
            hbm["maxDevice"] = worst
            hbm["maxDeviceBytes"] = int(per_device[worst])
            hbm["perDeviceBytes"] = {d: int(v) for d, v in
                                     sorted(per_device.items())}
            if resident:
                hbm["residentBytesByDevice"] = {
                    d: int(v) for d, v in sorted(resident.items())}
        subsystems["hbm"] = hbm

    # deadline pressure: errorCode-250 partials + killed queries as a
    # running total (rates are the history/SLO layer's job)
    killed = sum(v for _k, v in _family_items(counters, "queries_killed"))
    code250 = sum(v for _k, v in _family_items(
        counters, "broker_error_code_250"))
    expired = sum(v for _k, v in _family_items(
        counters, "deadline_expired"))
    subsystems["deadlines"] = {
        "ok": True,
        "errorCode250": code250, "queriesKilled": killed,
        "gatherExpired": expired}

    # replication (controller): SegmentStatusChecker gauges — ANY table
    # with segments under their configured replication flips the role
    # (and, through the sweep, /cluster/health) to degraded; repair
    # draining segments_missing_replicas to zero is the recovery signal
    missing_by_table = {k: v for k, v in _family_items(
        gauges, "segments_missing_replicas")}
    offline = sum(v for _k, v in _family_items(gauges, "segments_offline"))
    if missing_by_table or offline:
        def _table_of(key: str) -> str:
            # segments_missing_replicas{table="x_OFFLINE"} -> x_OFFLINE
            m = re.search(r'table="([^"]*)"', key)
            return m.group(1) if m else key

        under = sorted(_table_of(k) for k, v in missing_by_table.items()
                       if v)
        subsystems["replication"] = {
            "ok": not under and not offline,
            "segmentsMissingReplicas": int(sum(missing_by_table.values())),
            "segmentsOffline": int(offline),
            "underReplicated": under}

    # SLO watchdog: the only subsystem allowed to flip the verdict from
    # burn-rate math (multi-window — resistant to blips by construction)
    dog = get_watchdog(role)
    slo_verdicts = dog.verdicts() if dog is not None else {}
    slo_breached = any(v.get("breached") for v in slo_verdicts.values())
    subsystems["slo"] = {"ok": not slo_breached, "targets": slo_verdicts}

    # brownout ladder (health/brownout.py): any engaged rung means the
    # role is deliberately degraded — visible here and, through the
    # sweep, in /cluster/health
    from pinot_tpu.health.brownout import get_brownout
    ctrl = get_brownout(role)
    if ctrl is not None:
        subsystems["brownout"] = ctrl.payload()

    degraded = [name for name, sub in subsystems.items()
                if not sub.get("ok", True)]
    return {
        "role": role,
        "verdict": "degraded" if degraded else "live",
        "degraded": degraded,
        "subsystems": subsystems,
        "historySamples": len(get_history(role)),
        "ts": sample["ts"],
    }


@dataclass
class ScrapeTarget:
    """One scrapeable instance: either an HTTP base url (a role's admin
    / controller / broker surface) or an in-process fetch callable
    (embedded clusters) returning the same payload shape."""

    instance_id: str
    url: str = ""
    #: () -> {"health": <role_health_summary>, "sample": <registry sample>}
    fetch: Optional[Callable[[], dict]] = None
    role: str = "server"
    extra: dict = field(default_factory=dict)


class ClusterHealthMonitor:
    """Periodic fleet sweep over scrape targets + heartbeat liveness.

    ``targets_fn`` re-resolves per sweep (instances come and go);
    ``liveness_fn`` returns {instance_id: heartbeat age seconds} (absent
    id = no liveness signal, reported as "unknown"). Every per-target
    failure is caught and folded into that instance's verdict — a sweep
    NEVER raises, because the health plane failing is exactly when the
    operator needs it."""

    def __init__(self, targets_fn: Callable[[], List[ScrapeTarget]],
                 liveness_fn: Optional[Callable[[], Dict[str, float]]] = None,
                 interval_s: float = 5.0, timeout_s: float = 2.0,
                 liveness_ttl_s: float = 15.0, metrics=None,
                 role: str = "controller"):
        self.targets_fn = targets_fn
        self.liveness_fn = liveness_fn
        self.interval_s = max(0.05, float(interval_s))
        self.timeout_s = max(0.1, float(timeout_s))
        self.liveness_ttl_s = float(liveness_ttl_s)
        self._metrics = metrics if metrics is not None \
            else get_registry(role)
        self._last: Optional[dict] = None
        self._samples: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- scraping -------------------------------------------------------
    def _scrape(self, t: ScrapeTarget) -> dict:
        if t.fetch is not None:
            return t.fetch()
        out = {}
        for key, path in (("health", "/debug/health"),
                          ("sample", "/debug/metrics/sample")):
            with urllib.request.urlopen(t.url.rstrip("/") + path,
                                        timeout=self.timeout_s) as resp:
                out[key] = json.loads(resp.read())
        return out

    def _try_scrape(self, t: ScrapeTarget):
        """(payload, None) on success, (None, reason) on any failure —
        the pool-safe wrapper sweep() fans out over."""
        try:
            return self._scrape(t), None
        except Exception as e:  # noqa: BLE001 — degraded, never throw
            return None, f"{type(e).__name__}: {e}"

    def sweep(self, now: Optional[float] = None) -> dict:
        """One full pass; returns (and retains) the /cluster/health
        payload. Never raises."""
        now = now if now is not None else time.time()
        try:
            targets = list(self.targets_fn())
        except Exception:  # noqa: BLE001 — the sweep must survive
            log.exception("health sweep: targets_fn failed")
            targets = []
        ages: Dict[str, float] = {}
        if self.liveness_fn is not None:
            try:
                ages = dict(self.liveness_fn())
            except Exception:  # noqa: BLE001
                log.exception("health sweep: liveness_fn failed")
        instances: Dict[str, dict] = {}
        samples: Dict[str, dict] = {}
        # scrape CONCURRENTLY: serially, a handful of accept-but-hang
        # instances would each eat a full scrape timeout and blow the
        # sweep past its interval for the whole fleet
        if targets:
            with ThreadPoolExecutor(
                    max_workers=min(8, len(targets)),
                    thread_name_prefix="health-scrape") as pool:
                scraped_by_id = dict(pool.map(
                    lambda t: (t.instance_id, self._try_scrape(t)),
                    targets))
        for t in targets:
            entry: dict = {"role": t.role, **t.extra}
            age = ages.get(t.instance_id)
            if age is None:
                entry["liveness"] = "unknown"
            else:
                entry["lastHeartbeatAgeSeconds"] = round(age, 3)
                entry["liveness"] = ("live" if age <= self.liveness_ttl_s
                                     else "stale")
            scraped, err = scraped_by_id.get(t.instance_id, (None, None))
            if scraped is not None:
                health = scraped.get("health") or {}
                entry["reachable"] = True
                entry["verdict"] = health.get("verdict", "live")
                entry["degraded"] = health.get("degraded", [])
                entry["subsystems"] = health.get("subsystems", {})
                sample = scraped.get("sample")
                if sample:
                    samples[t.instance_id] = sample
            else:
                self._metrics.add_meter("cluster_scrape_failures")
                entry["reachable"] = False
                entry["verdict"] = "degraded"
                entry["reason"] = f"scrape failed: {err}"
            if entry.get("liveness") == "stale":
                entry["verdict"] = "degraded"
                entry.setdefault("reason", "heartbeat stale")
            instances[t.instance_id] = entry
        live = sum(1 for e in instances.values()
                   if e.get("verdict") == "live")
        degraded = len(instances) - live
        self._metrics.set_gauge("cluster_instances_live", live)
        self._metrics.set_gauge("cluster_instances_degraded", degraded)
        payload = {
            "ts": now,
            "verdict": "degraded" if degraded else "live",
            "instancesLive": live,
            "instancesDegraded": degraded,
            "instances": instances,
        }
        with self._lock:
            self._last = payload
            self._samples = samples
        return payload

    # -- payloads -------------------------------------------------------
    def cluster_health(self) -> dict:
        """Last sweep's verdict payload (sweeps synchronously when no
        sweep has run yet — the first GET must not answer empty)."""
        with self._lock:
            last = self._last
        return last if last is not None else self.sweep()

    def cluster_metrics(self) -> dict:
        """Fleet-wide rollup from the last sweep's samples: counters
        summed across instances per family+labels, gauges kept
        per-instance (a gauge sum across hosts is rarely meaningful)."""
        with self._lock:
            samples = dict(self._samples)
            swept = self._last is not None
        if not samples and not swept:
            self.sweep()  # first GET before the first tick: answer live
            with self._lock:
                samples = dict(self._samples)
        counters: Dict[str, float] = {}
        gauges: Dict[str, Dict[str, float]] = {}
        for iid, s in sorted(samples.items()):
            for k, v in s.get("counters", {}).items():
                counters[k] = counters.get(k, 0.0) + float(v)
            for k, v in s.get("gauges", {}).items():
                gauges.setdefault(iid, {})[k] = v
        return {"ts": time.time(), "instances": sorted(samples),
                "counters": counters, "gaugesByInstance": gauges}

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="cluster-health-monitor")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 — belt over sweep's braces
                log.exception("cluster health sweep failed")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


def _role_of_tags(tags) -> str:
    for t in ("minion", "broker", "cache_server"):
        if t in tags:
            return t
    return "server"


def make_cluster_monitor(state, coordination=None,
                         config=None) -> ClusterHealthMonitor:
    """The controller's fleet monitor over its live cluster state:
    targets re-resolve per sweep from registered instances carrying an
    ``admin_url`` (servers' DebugHttpServer, brokers' HTTP edge, minion
    workers), plus an in-process self-target for the controller role;
    liveness rides the coordination server's heartbeat ages."""
    from pinot_tpu.utils.config import PinotConfiguration
    cfg = config or PinotConfiguration()
    controller_cfg = cfg

    def controller_self() -> dict:
        return {"health": role_health_summary("controller",
                                              config=controller_cfg),
                "sample": get_registry("controller").sample()}

    def targets_fn():
        out = [ScrapeTarget(instance_id="controller",
                            fetch=controller_self, role="controller")]
        with state._lock:
            insts = list(state.instances.values())
        for inst in insts:
            if not inst.admin_url:
                continue
            out.append(ScrapeTarget(
                instance_id=inst.instance_id, url=inst.admin_url,
                role=_role_of_tags(inst.tags)))
        return out

    liveness_fn = (coordination.heartbeat_ages
                   if coordination is not None else None)
    ttl = (coordination.LIVENESS_TTL_S if coordination is not None
           else cfg.get_float("pinot.coordination.liveness.ttl.seconds"))
    return ClusterHealthMonitor(
        targets_fn, liveness_fn=liveness_fn,
        interval_s=cfg.get_float("pinot.cluster.health.interval.seconds"),
        timeout_s=cfg.get_float(
            "pinot.cluster.health.scrape.timeout.seconds"),
        liveness_ttl_s=ttl)
