"""selfmetrics: the per-role metrics history as a queryable table.

The dogfood leg of the health plane (ROADMAP item 5's first real
consumer): a role's :class:`~pinot_tpu.health.history.MetricsHistory`
ring materializes into a real immutable segment — table ``selfmetrics``,
one row per (sample, numeric series) — and the time-series engine
(timeseries/engine.py simpleql) queries it through the regular
:class:`~pinot_tpu.query.executor.QueryExecutor` leaf bridge. The
system answers questions about itself with its own query engine:

    fetch(selfmetrics, value, ts, 1000, 1060, 10)
      | where(family = 'queries') | sum() | rate()

Columns:

* ``ts``     — sample wall-clock time, whole seconds (LONG)
* ``name``   — full flat series name incl. labels + timer field suffix
               (``query_execution{table="t"}:p99``)
* ``family`` — bare metric family (``query_execution``) — the usual
               ``where(family = '…')`` filter key
* ``kind``   — counter | gauge | timer
* ``role``   — the sampled role
* ``value``  — the numeric observation (DOUBLE); counters are cumulative
               (pipe through ``rate()`` for per-second rates)
"""
from __future__ import annotations

import os
import shutil
import tempfile
from typing import List, Optional

from pinot_tpu.health.history import MetricsHistory, get_history

#: timer snapshot fields worth exposing as series (suffixing the name)
_TIMER_FIELDS = ("count", "sum_ms", "max_ms", "p50", "p95", "p99")


def _family(flat_name: str) -> str:
    return flat_name.partition("{")[0]


def history_rows(history: MetricsHistory, role: str = "server",
                 window_s: Optional[float] = None) -> List[tuple]:
    """(ts, name, family, kind, role, value) per numeric series per
    sample, oldest first."""
    rows: List[tuple] = []
    for s in history.samples(window_s):
        ts = int(s.get("ts", 0.0))
        srole = s.get("role", role)
        for k, v in s.get("counters", {}).items():
            rows.append((ts, k, _family(k), "counter", srole, float(v)))
        for k, v in s.get("gauges", {}).items():
            rows.append((ts, k, _family(k), "gauge", srole, float(v)))
        for k, t in s.get("timers", {}).items():
            for f in _TIMER_FIELDS:
                rows.append((ts, f"{k}:{f}", _family(k), "timer", srole,
                             float(t.get(f, 0.0))))
    return rows


def materialize_segment(out_dir: str, role: str = "server",
                        history: Optional[MetricsHistory] = None,
                        window_s: Optional[float] = None,
                        segment_name: str = "selfmetrics_0"):
    """Build + load one immutable ``selfmetrics`` segment from the
    role's history ring. Raises ValueError on an empty history — a
    zero-doc segment would answer every query with silence that is
    indistinguishable from 'sampler never ran'."""
    import numpy as np

    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    rows = history_rows(history if history is not None
                        else get_history(role), role=role,
                        window_s=window_s)
    if not rows:
        raise ValueError(
            f"no metrics-history samples for role {role!r} — is the "
            f"sampler running (pinot.metrics.history.enabled)?")
    schema = Schema("selfmetrics", [
        FieldSpec("ts", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("name", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("family", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("kind", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("role", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("value", DataType.DOUBLE, FieldType.METRIC)])
    cols = {
        "ts": np.array([r[0] for r in rows], np.int64),
        "name": np.array([r[1] for r in rows], object),
        "family": np.array([r[2] for r in rows], object),
        "kind": np.array([r[3] for r in rows], object),
        "role": np.array([r[4] for r in rows], object),
        "value": np.array([r[5] for r in rows], np.float64),
    }
    seg_dir = os.path.join(out_dir, segment_name)
    SegmentCreator(TableConfig(name="selfmetrics"), schema).build(
        cols, seg_dir, segment_name)
    return load_segment(seg_dir)


def query_history(simpleql: str, role: str = "server",
                  history: Optional[MetricsHistory] = None,
                  window_s: Optional[float] = None,
                  use_tpu: bool = False, engine=None):
    """Answer a simpleql query over the role's own metrics history:
    materialize the ring into a throwaway segment and run the
    time-series plan through the regular single-process executor (the
    engine's leaf bridge — full SQL pushdown, device offload when the
    shape qualifies). Pass ``use_tpu=True`` (or an existing ``engine``)
    to route the dashboard's bucket group-by through the device
    time-bucket leg as a third tenant-isolated workload beside queries
    and log search. Returns a TimeSeriesBlock."""
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.timeseries.engine import query as ts_query

    tmp = tempfile.mkdtemp(prefix="selfmetrics-")
    try:
        seg = materialize_segment(tmp, role=role, history=history,
                                  window_s=window_s)
        ex = QueryExecutor([seg], use_tpu=use_tpu, engine=engine)
        return ts_query(simpleql, ex)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
