"""SLO burn-rate watchdog over the per-role metrics history.

Declarative targets (``pinot.slo.*`` knobs) evaluated as MULTI-WINDOW
burn rates (the Google SRE workbook alerting shape): each target's
error-budget consumption rate is computed over a short and a long
trailing window of :class:`~pinot_tpu.health.history.MetricsHistory`
samples, and a breach requires BOTH windows over the threshold — the
short window makes the alert fast, the long window keeps a one-sample
blip from paging anyone. Outputs, per evaluation:

* ``slo_burn_rate{slo=…}`` gauge (the short-window burn — the fast
  signal dashboards plot);
* on a breach ONSET, one structured ``SLO_BREACH`` JSON log line and an
  ``slo_breaches{slo=…}`` meter bump (onset-only: a sustained breach is
  one incident, not one log line per sampling tick);
* a per-target verdict served inside ``/debug/health`` and rolled into
  the controller's ``/cluster/health``.

Targets (a knob left at 0 disables its target):

* ``pinot.slo.query.p99.ms`` — queries whose measured latency exceeded
  the target, counted at the recording sites into the
  ``slo_latency_bad`` meter and read back as WINDOWED counter deltas:
  burn = (bad queries / total queries over the window) /
  ``pinot.slo.latency.budget``. Deliberately NOT the registry timer
  p99s: those quantiles come from a lifetime equal-probability
  reservoir (utils/metrics.py Timer, algorithm R), so every history
  sample carries the same slowly-moving cumulative value — a burn
  computed from them would stay breached long after latency recovered
  and the short/long windows could never disagree.
* ``pinot.slo.error.rate`` — error responses (exceptions + deadline
  kills) per query over the window must stay at/under the target rate.
  burn = observed rate / target rate.
* ``pinot.slo.freshness.ms`` — worst per-partition ingestion lag per
  sample must stay at/under the target; the budget is the allowed
  bad-sample fraction. burn = observed bad fraction / budget.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from pinot_tpu.health.history import MetricsHistory, family_items
from pinot_tpu.utils.metrics import get_registry

slo_log = logging.getLogger("pinot_tpu.slo")

#: counter the latency burn reads: queries over the configured p99
#: target, bumped where the latency is measured (broker handle(),
#: server _execute_inner) — see the module docstring for why this is a
#: counter and not the registry timer quantiles
_LATENCY_BAD_FAMILY = "slo_latency_bad"
#: counter families summed into the error-rate numerator. NOT
#: broker_error_code_250: the broker bumps broker_query_errors for ANY
#: exception entry, deadline partials included, so adding the
#: 250-specific family would double-count every deadline miss (it
#: stays a /cluster/health diagnostic). Server-side kills vs raises
#: are mutually exclusive branches — both belong.
_ERROR_FAMILIES = ("broker_query_errors", "query_exceptions",
                   "queries_killed")
_QUERY_FAMILIES = ("broker_queries", "queries")


class SloWatchdog:
    """Evaluates the configured targets over one role's history; runs as
    a :class:`~pinot_tpu.health.history.MetricsSampler` hook (once per
    sampling tick) or synchronously via :meth:`evaluate` in tests."""

    def __init__(self, role: str, history: MetricsHistory, config=None,
                 metrics=None):
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = config or PinotConfiguration()
        self.role = role
        self.history = history
        self._metrics = metrics if metrics is not None \
            else get_registry(role)
        self.p99_target_ms = cfg.get_float("pinot.slo.query.p99.ms")
        self.error_rate_target = cfg.get_float("pinot.slo.error.rate")
        self.freshness_target_ms = cfg.get_float("pinot.slo.freshness.ms")
        self.short_s = max(1.0, cfg.get_float(
            "pinot.slo.window.short.seconds"))
        self.long_s = max(self.short_s, cfg.get_float(
            "pinot.slo.window.long.seconds"))
        self.burn_threshold = max(0.0, cfg.get_float(
            "pinot.slo.burn.threshold"))
        self.latency_budget = min(1.0, max(1e-6, cfg.get_float(
            "pinot.slo.latency.budget")))
        #: slo name -> currently-breached flag (onset edge detection)
        self._breached: Dict[str, bool] = {}
        self._verdicts: Dict[str, dict] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self.p99_target_ms or self.error_rate_target
                    or self.freshness_target_ms)

    # -- burn-rate math -------------------------------------------------
    def _bad_fraction_burn(self, series: List[Tuple[float, float]],
                           target: float) -> float:
        """Burn for sample-fraction targets (freshness): the fraction
        of window samples whose value exceeded the target, divided by
        the budgeted fraction. 0.0 with no samples — an idle role has
        burned no budget."""
        if not series:
            return 0.0
        bad = sum(1 for _ts, v in series if v > target)
        return (bad / len(series)) / self.latency_budget

    def _latency_burn(self, window_s: float, now: float) -> float:
        """(bad queries / total queries over the window) / budget —
        windowed counter deltas, 0.0 when the role served nothing."""
        bad = self.history.counter_sum_delta(
            _LATENCY_BAD_FAMILY, window_s, now=now)[0]
        queries = sum(self.history.counter_sum_delta(f, window_s, now=now)[0]
                      for f in _QUERY_FAMILIES)
        if queries <= 0:
            return 0.0
        return (bad / queries) / self.latency_budget

    def _error_burn(self, window_s: float, now: float) -> float:
        errors = sum(self.history.counter_sum_delta(f, window_s, now=now)[0]
                     for f in _ERROR_FAMILIES)
        queries = sum(self.history.counter_sum_delta(f, window_s, now=now)[0]
                      for f in _QUERY_FAMILIES)
        if queries <= 0:
            return 0.0
        return (errors / queries) / self.error_rate_target

    def _freshness_series(self, window_s: float,
                          now: float) -> List[Tuple[float, float]]:
        """Per-sample worst ingestion lag across partitions."""
        out: List[Tuple[float, float]] = []
        for s in self.history.samples(window_s, now=now):
            worst: Optional[float] = None
            for _k, v in family_items(s.get("gauges", {}),
                                      "ingestion_delay_ms"):
                if worst is None or float(v) > worst:
                    worst = float(v)
            if worst is not None:
                out.append((float(s["ts"]), worst))
        return out

    # -- evaluation -----------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, dict]:
        """One multi-window pass over every configured target. Returns
        (and retains, for /debug/health) {slo name: verdict dict}."""
        now = now if now is not None else time.time()
        targets = []
        if self.p99_target_ms:
            targets.append(("query.p99.ms", self.p99_target_ms,
                            self._latency_burn))
        if self.error_rate_target:
            targets.append(("error.rate", self.error_rate_target,
                            self._error_burn))
        if self.freshness_target_ms:
            targets.append((
                "freshness.ms", self.freshness_target_ms,
                lambda w, n: self._bad_fraction_burn(
                    self._freshness_series(w, n), self.freshness_target_ms)))
        verdicts: Dict[str, dict] = {}
        for name, target, burn_fn in targets:
            burn_short = burn_fn(self.short_s, now)
            burn_long = burn_fn(self.long_s, now)
            breached = (burn_short > self.burn_threshold
                        and burn_long > self.burn_threshold)
            self._metrics.set_gauge("slo_burn_rate", round(burn_short, 4),
                                    labels={"slo": name})
            with self._lock:
                was = self._breached.get(name, False)
                self._breached[name] = breached
            if breached and not was:
                self._metrics.add_meter("slo_breaches",
                                        labels={"slo": name})
                slo_log.warning("SLO_BREACH %s", json.dumps({
                    "role": self.role, "slo": name, "target": target,
                    "burnShort": round(burn_short, 4),
                    "burnLong": round(burn_long, 4),
                    "windowShortS": self.short_s,
                    "windowLongS": self.long_s,
                    "threshold": self.burn_threshold}, default=str))
            verdicts[name] = {
                "target": target,
                "burnShort": round(burn_short, 4),
                "burnLong": round(burn_long, 4),
                "breached": breached,
            }
        with self._lock:
            self._verdicts = verdicts
        return verdicts

    def verdicts(self) -> Dict[str, dict]:
        """Last evaluation's per-target verdicts (may be empty before
        the first tick or with no targets configured)."""
        with self._lock:
            return dict(self._verdicts)

    def breached(self) -> bool:
        with self._lock:
            return any(v.get("breached") for v in self._verdicts.values())


# -- per-role singletons (populated by history.start_sampling) ---------------
_watchdogs: Dict[str, SloWatchdog] = {}
_lock = threading.Lock()


def get_watchdog(role: str = "server") -> Optional[SloWatchdog]:
    with _lock:
        return _watchdogs.get(role)


def _register_watchdog(role: str, dog: Optional[SloWatchdog]) -> None:
    with _lock:
        if dog is None:
            _watchdogs.pop(role, None)
        else:
            _watchdogs[role] = dog
