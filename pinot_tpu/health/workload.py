"""Per-(tenant, table, plan-fingerprint) workload cost rollup.

Answers "which tenant/table/plan is eating the cluster": every finished
query's :class:`~pinot_tpu.utils.accounting.QueryUsage` — device kernel
ms (coalesced launches split by doc share), rows/bytes scanned,
host->device transfer bytes, cache hit/miss bytes, CPU ns, wall ms —
accumulates into one :class:`WorkloadStats` bucket per attribution key.
``/debug/workload`` serves the top-K by cost; per-tenant cost gauges
(``workload_tenant_cost_ms``) feed dashboards and the cluster rollup.

Cost is defined as ``device_kernel_ms + cpu_ms``: the two resources a
query actually occupies exclusively. Wall ms is reported beside it but
not summed into cost — wall time overlaps across concurrent queries.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from pinot_tpu.utils.accounting import QueryUsage
from pinot_tpu.utils.metrics import get_registry

_Key = Tuple[str, str, str]  # (tenant, table, plan fingerprint)


@dataclass
class WorkloadStats:
    tenant: str
    table: str
    plan_fingerprint: str
    queries: int = 0
    errors: int = 0
    wall_ms: float = 0.0
    cpu_ms: float = 0.0
    device_kernel_ms: float = 0.0
    rows_scanned: int = 0
    bytes_scanned: int = 0
    transfer_bytes: int = 0
    cache_hit_bytes: int = 0
    cache_miss_bytes: int = 0
    last_seen: float = field(default_factory=time.time)

    @property
    def cost_ms(self) -> float:
        return self.device_kernel_ms + self.cpu_ms

    def to_dict(self) -> dict:
        return {
            "tenant": self.tenant, "table": self.table,
            "planFingerprint": self.plan_fingerprint,
            "queries": self.queries, "errors": self.errors,
            "costMs": round(self.cost_ms, 3),
            "wallMs": round(self.wall_ms, 3),
            "cpuMs": round(self.cpu_ms, 3),
            "deviceKernelMs": round(self.device_kernel_ms, 3),
            "rowsScanned": self.rows_scanned,
            "bytesScanned": self.bytes_scanned,
            "transferBytes": self.transfer_bytes,
            "cacheHitBytes": self.cache_hit_bytes,
            "cacheMissBytes": self.cache_miss_bytes,
            "lastSeen": self.last_seen,
        }


#: fallback attribution values — a blank key would make distinct
#: workloads collide silently
UNATTRIBUTED = "-"


class WorkloadRegistry:
    """Bounded per-role rollup; eviction drops the cheapest-and-oldest
    entry so the expensive workloads an operator hunts survive churn."""

    MAX_ENTRIES = 512

    def __init__(self, role: str = "server", metrics=None,
                 max_entries: Optional[int] = None):
        self.role = role
        self._entries: Dict[_Key, WorkloadStats] = {}
        self._tenant_cost_ms: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._metrics = metrics if metrics is not None \
            else get_registry(role)
        self.max_entries = max_entries or self.MAX_ENTRIES

    # -- write side ----------------------------------------------------
    def record_usage(self, usage: QueryUsage, *, wall_ms: float = 0.0,
                     error: bool = False) -> None:
        """Fold one finished query's usage record in (the server path:
        ServerQueryExecutor charges usage during execution and records
        it at finish_query)."""
        self.record(
            tenant=usage.tenant, table=usage.table,
            fingerprint=usage.plan_fingerprint,
            wall_ms=wall_ms or (time.time() - usage.start_time) * 1e3,
            cpu_ms=usage.cpu_ns / 1e6,
            device_kernel_ms=usage.device_kernel_ms,
            rows_scanned=usage.rows_scanned,
            bytes_scanned=usage.bytes_scanned,
            transfer_bytes=usage.transfer_bytes,
            cache_hit_bytes=usage.cache_hit_bytes,
            cache_miss_bytes=usage.cache_miss_bytes,
            error=error)

    def record(self, *, tenant: str, table: str, fingerprint: str,
               wall_ms: float = 0.0, cpu_ms: float = 0.0,
               device_kernel_ms: float = 0.0, rows_scanned: int = 0,
               bytes_scanned: int = 0, transfer_bytes: int = 0,
               cache_hit_bytes: int = 0, cache_miss_bytes: int = 0,
               error: bool = False) -> None:
        key = (tenant or UNATTRIBUTED, table or UNATTRIBUTED,
               fingerprint or UNATTRIBUTED)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                if len(self._entries) >= self.max_entries:
                    self._evict_locked()
                e = self._entries[key] = WorkloadStats(*key)
            e.queries += 1
            if error:
                e.errors += 1
            e.wall_ms += float(wall_ms)
            e.cpu_ms += float(cpu_ms)
            e.device_kernel_ms += float(device_kernel_ms)
            e.rows_scanned += int(rows_scanned)
            e.bytes_scanned += int(bytes_scanned)
            e.transfer_bytes += int(transfer_bytes)
            e.cache_hit_bytes += int(cache_hit_bytes)
            e.cache_miss_bytes += int(cache_miss_bytes)
            e.last_seen = time.time()
            tcost = self._tenant_cost_ms.get(key[0], 0.0) \
                + float(device_kernel_ms) + float(cpu_ms)
            self._tenant_cost_ms[key[0]] = tcost
        # gauge OUTSIDE the registry lock (the metrics registry has its
        # own); per-tenant cost is the dashboard-facing series
        self._metrics.set_gauge("workload_tenant_cost_ms", round(tcost, 3),
                                labels={"tenant": key[0]})

    def _evict_locked(self) -> None:
        """Drop the lowest-(cost, recency) entry to admit a new one."""
        victim = min(self._entries.values(),
                     key=lambda e: (e.cost_ms, e.last_seen))
        del self._entries[(victim.tenant, victim.table,
                           victim.plan_fingerprint)]

    # -- read side -----------------------------------------------------
    def top(self, k: int = 20, by: str = "cost_ms") -> list:
        with self._lock:
            entries = list(self._entries.values())
        entries.sort(key=lambda e: getattr(e, by, 0.0), reverse=True)
        return [e.to_dict() for e in entries[:max(1, int(k))]]

    def tenants(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._tenant_cost_ms)

    def payload(self, k: int = 20) -> dict:
        """The /debug/workload JSON: top-K by cost + per-tenant totals."""
        return {"role": self.role, "topK": self.top(k),
                "tenantCostMs": {t: round(v, 3)
                                 for t, v in self.tenants().items()}}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._tenant_cost_ms.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# -- per-role singletons ----------------------------------------------------
_registries: Dict[str, WorkloadRegistry] = {}
_reg_lock = threading.Lock()


def get_workload(role: str = "server") -> WorkloadRegistry:
    with _reg_lock:
        reg = _registries.get(role)
        if reg is None:
            reg = _registries[role] = WorkloadRegistry(role)
        return reg
