"""Ingestion: stream SPI, record transforms, mutable segments, realtime
consumption lifecycle, batch jobs.

Reference parity: pinot-spi stream/ (36-file consumer SPI),
pinot-segment-local recordtransformer/ + realtime/impl/ mutable indexes,
pinot-core data/manager/realtime/RealtimeSegmentDataManager.java:122
(SURVEY.md §3.3 call stack).
"""
from pinot_tpu.ingest.stream import (
    LongMsgOffset, MessageBatch, PartitionGroupConsumer, StreamConfig,
    StreamConsumerFactory, StreamMessage)
from pinot_tpu.ingest.memory_stream import InMemoryStream, InMemoryStreamConsumerFactory
from pinot_tpu.ingest.mutable_segment import MutableSegment
from pinot_tpu.ingest.transforms import TransformPipeline

__all__ = [
    "LongMsgOffset", "MessageBatch", "PartitionGroupConsumer", "StreamConfig",
    "StreamConsumerFactory", "StreamMessage", "InMemoryStream",
    "InMemoryStreamConsumerFactory", "MutableSegment", "TransformPipeline",
]
