"""Batch ingestion: files -> segments.

Reference parity: pinot-plugins pinot-batch-ingestion (standalone runner)
+ pinot-input-format record readers (csv/json/avro/parquet...) feeding
SegmentIndexCreationDriverImpl (SURVEY.md §3.5). Readers yield record
dicts; the job runs them through the TransformPipeline and builds one
segment per input file (or per row-count split).

Formats: CSV and JSON-lines natively; parquet/avro gated on wheels being
present (pyarrow/fastavro are not in this image — a clear error names the
missing dependency, matching the plugin-not-installed behavior).
"""
from __future__ import annotations

import csv
import glob as globlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from pinot_tpu.ingest.transforms import TransformPipeline
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.segment.creator import SegmentCreator


def read_records(path: str, fmt: Optional[str] = None) -> Iterator[Dict[str, Any]]:
    """One file -> record dicts. Readers resolve through the plugin
    registry (ref RecordExtractor plugins loaded by PluginManager); the
    built-in formats below register through the same seam."""
    from pinot_tpu.utils import plugins
    fmt = (fmt or _infer_format(path)).lower()
    try:
        reader = plugins.get("input_format", fmt)
    except KeyError as e:
        raise ValueError(f"unsupported input format {fmt!r}: {e}") from e
    yield from reader(path)


def _read_csv(path: str) -> Iterator[Dict[str, Any]]:
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            yield {k: (None if v == "" else v) for k, v in row.items()}


def _read_json(path: str) -> Iterator[Dict[str, Any]]:
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "[":
            yield from json.load(f)
        else:
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)


def _read_parquet(path: str) -> Iterator[Dict[str, Any]]:
    try:
        import pyarrow.parquet as pq  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "parquet input requires the pyarrow wheel (input-format "
            "plugin not installed)") from e
    for batch in pq.ParquetFile(path).iter_batches():
        yield from batch.to_pylist()


def _read_avro(path: str) -> Iterator[Dict[str, Any]]:
    try:
        import fastavro  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "avro input requires the fastavro wheel (input-format "
            "plugin not installed)") from e
    with open(path, "rb") as f:
        yield from fastavro.reader(f)


def _register_builtin_formats() -> None:
    from pinot_tpu.utils import plugins
    plugins.register("input_format", "csv", _read_csv)
    for name in ("json", "jsonl", "ndjson"):
        plugins.register("input_format", name, _read_json)
    plugins.register("input_format", "parquet", _read_parquet)
    plugins.register("input_format", "avro", _read_avro)


_register_builtin_formats()


def _infer_format(path: str) -> str:
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    return {"csv": "csv", "json": "json", "jsonl": "jsonl",
            "ndjson": "ndjson", "parquet": "parquet", "avro": "avro"}.get(ext, "csv")


@dataclass
class IngestionJobSpec:
    """Ref batch-ingestion job spec yaml (SegmentGenerationJobSpec)."""
    input_pattern: str                    # glob of input files
    output_dir: str
    table_config: TableConfig = None      # type: ignore[assignment]
    schema: Schema = None                 # type: ignore[assignment]
    input_format: Optional[str] = None
    segment_name_prefix: Optional[str] = None
    rows_per_segment: Optional[int] = None  # None = one segment per file


def run_ingestion_job(spec: IngestionJobSpec) -> List[str]:
    """Ref LaunchDataIngestionJobCommand -> SegmentGenerationJobRunner.
    Returns the created segment directories."""
    files = sorted(globlib.glob(spec.input_pattern))
    if not files:
        raise FileNotFoundError(f"no inputs match {spec.input_pattern!r}")
    pipeline = TransformPipeline(spec.table_config, spec.schema)
    creator = SegmentCreator(spec.table_config, spec.schema)
    prefix = spec.segment_name_prefix or spec.table_config.name
    out_dirs: List[str] = []
    seq = 0
    skipped = 0
    CHUNK = 4096
    for path in files:
        buf: List[Dict[str, Any]] = []
        chunk: List[Dict[str, Any]] = []

        def drain(chunk_rows):
            # columnar batch transform: one expression pass per chunk;
            # poison rows come back as per-row exceptions — skipped +
            # logged, never failing the job (the realtime consumer's
            # per-record guard, mirrored)
            nonlocal skipped, seq, buf
            for out in pipeline.transform_batch(chunk_rows):
                if isinstance(out, Exception):
                    skipped += 1
                    if skipped <= 10:
                        import logging
                        logging.getLogger(__name__).warning(
                            "skipping untransformable record in %s: %r",
                            path, out)
                    continue
                if out is not None:
                    buf.append(out)
                if spec.rows_per_segment and \
                        len(buf) >= spec.rows_per_segment:
                    out_dirs.append(_flush(creator, spec, prefix, seq, buf))
                    seq += 1
                    buf = []

        for rec in read_records(path, spec.input_format):
            chunk.append(rec)
            if len(chunk) >= CHUNK:
                drain(chunk)
                chunk = []
        if chunk:
            drain(chunk)
        if buf:
            out_dirs.append(_flush(creator, spec, prefix, seq, buf))
            seq += 1
    return out_dirs


def _flush(creator: SegmentCreator, spec: IngestionJobSpec, prefix: str,
           seq: int, rows: List[Dict[str, Any]]) -> str:
    columns = _rows_to_columns(rows, spec.schema)
    name = f"{prefix}_{seq}"
    out_dir = os.path.join(spec.output_dir, name)
    creator.build(columns, out_dir, name)
    return out_dir


def _rows_to_columns(rows: List[Dict[str, Any]], schema: Schema) -> Dict[str, list]:
    cols: Dict[str, list] = {}
    for spec in schema.fields:
        if spec.virtual:
            continue
        cols[spec.name] = [r.get(spec.name) for r in rows]
    return cols
