"""In-memory stream: the embedded-Kafka analog for tests and quickstarts.

Reference parity: the test-scope StreamDataServerStartable embedded Kafka
(pinot-plugins/pinot-stream-ingestion/pinot-kafka-base) used by
BaseClusterIntegrationTest — here a thread-safe in-process topic with
numbered partitions and Long offsets.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from pinot_tpu.ingest.stream import (
    LongMsgOffset, MessageBatch, PartitionGroupConsumer, StreamConfig,
    StreamConsumerFactory, StreamMessage, StreamMetadataProvider,
    register_stream_factory)


class InMemoryStream:
    """A topic: N partitions of append-only message lists."""

    _topics: Dict[str, "InMemoryStream"] = {}
    _registry_lock = threading.Lock()

    def __init__(self, topic: str, num_partitions: int = 1):
        self.topic = topic
        self.num_partitions = num_partitions
        self._partitions: List[List[StreamMessage]] = [
            [] for _ in range(num_partitions)]
        self._lock = threading.Lock()
        with InMemoryStream._registry_lock:
            InMemoryStream._topics[topic] = self

    @classmethod
    def get(cls, topic: str) -> "InMemoryStream":
        with cls._registry_lock:
            s = cls._topics.get(topic)
        if s is None:
            raise KeyError(f"in-memory topic {topic!r} does not exist")
        return s

    @classmethod
    def delete(cls, topic: str) -> None:
        with cls._registry_lock:
            cls._topics.pop(topic, None)

    def publish(self, record: Dict[str, Any], partition: Optional[int] = None,
                key: Optional[str] = None,
                ts_ms: Optional[int] = None) -> LongMsgOffset:
        """ts_ms: event timestamp (feeds IngestionDelayTracker lag and
        the --ingest bench's freshness measurement)."""
        if partition is None:
            partition = (hash(key) if key is not None else 0) % self.num_partitions
        with self._lock:
            part = self._partitions[partition]
            off = LongMsgOffset(len(part))
            part.append(StreamMessage(value=record, offset=off, key=key,
                                      timestamp_ms=ts_ms))
            return off

    def fetch(self, partition: int, start: LongMsgOffset,
              max_messages: int = 10_000) -> MessageBatch:
        with self._lock:
            part = self._partitions[partition]
            msgs = part[start.offset:start.offset + max_messages]
            nxt = LongMsgOffset(start.offset + len(msgs))
            return MessageBatch(messages=list(msgs), next_offset=nxt)

    def latest_offset(self, partition: int) -> LongMsgOffset:
        with self._lock:
            return LongMsgOffset(len(self._partitions[partition]))


class _InMemoryConsumer(PartitionGroupConsumer):
    def __init__(self, topic: str, partition_id: int):
        self.topic = topic
        self.partition_id = partition_id

    def fetch_messages(self, start_offset: LongMsgOffset,
                       timeout_ms: int,
                       max_messages: int = 10_000) -> MessageBatch:
        return InMemoryStream.get(self.topic).fetch(
            self.partition_id, start_offset, max_messages=max_messages)


class _InMemoryMetadataProvider(StreamMetadataProvider):
    def __init__(self, topic: str):
        self.topic = topic

    def partition_ids(self) -> List[int]:
        return list(range(InMemoryStream.get(self.topic).num_partitions))

    def start_offset(self, partition_id: int, criteria: str) -> LongMsgOffset:
        if criteria == "largest":
            return InMemoryStream.get(self.topic).latest_offset(partition_id)
        return LongMsgOffset(0)


class InMemoryStreamConsumerFactory(StreamConsumerFactory):
    def create_partition_consumer(self, config: StreamConfig,
                                  partition_id: int) -> PartitionGroupConsumer:
        return _InMemoryConsumer(config.topic, partition_id)

    def create_metadata_provider(self, config: StreamConfig) -> StreamMetadataProvider:
        return _InMemoryMetadataProvider(config.topic)


register_stream_factory("inmemory", InMemoryStreamConsumerFactory())
