"""Mutable (consuming) segment: rows are queryable as they arrive.

Reference parity: pinot-segment-local
indexsegment/mutable/MutableSegmentImpl.java:515 (index(row)) and the
realtime/impl/ mutable column structures. Differences, deliberate:
  * columns append into amortized-doubling numpy buffers (the analog of
    FixedByteSVMutableForwardIndex's chunked buffers);
  * mutable dictionaries are insertion-ordered value<->id maps (unsorted,
    as in the reference) — so the query path treats mutable columns as
    raw values (value-space predicates) rather than sorted-dictId space,
    and the device engine leaves consuming segments to the host executor
    (they are small by construction: flush thresholds cap them).

Queries see a CONSISTENT SNAPSHOT: data_source() binds to num_docs at
call time (ref: reference queries read up to the indexed row count).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.models import DataType, FieldSpec, FieldType, Schema, TableConfig
from pinot_tpu.segment.bitmap import Bitmap
from pinot_tpu.segment.meta import ColumnMetadata, SegmentMetadata


class _MutableColumn:
    def __init__(self, spec: FieldSpec):
        self.spec = spec
        st = spec.data_type.stored_type
        self._np_dtype = spec.data_type.np_dtype
        self._fixed = self._np_dtype.kind in "iuf"
        if spec.single_value:
            if self._fixed:
                self._buf = np.empty(1024, dtype=self._np_dtype)
            else:
                self._buf: List[Any] = []
        else:
            self._values: List[List[Any]] = []
        self._null_docs: List[int] = []
        self.distinct: set = set()
        #: running estimate of indexed bytes (feeds the server-wide
        #: mutable-bytes ingestion budget — cheap incremental accounting,
        #: not exact heap usage)
        self.nbytes_est = 0

    #: per-value overhead estimate for variable-size (object) storage
    _OBJ_OVERHEAD = 56

    def append(self, doc_id: int, value: Any) -> None:
        spec = self.spec
        if value is None:
            self._null_docs.append(doc_id)
            value = (spec.default_null_value if spec.single_value
                     else [spec.default_null_value])
        if spec.single_value:
            if self._fixed:
                if doc_id >= len(self._buf):
                    self._buf = np.concatenate(
                        [self._buf, np.empty(len(self._buf), dtype=self._np_dtype)])
                self._buf[doc_id] = value
                self.nbytes_est += self._np_dtype.itemsize
            else:
                self._buf.append(value)
                self.nbytes_est += self._OBJ_OVERHEAD + (
                    len(value) if isinstance(value, (str, bytes)) else 8)
            self.distinct.add(value)
        else:
            self._values.append(list(value))
            for v in value:
                self.nbytes_est += self._OBJ_OVERHEAD + (
                    len(v) if isinstance(v, (str, bytes)) else 8)
            self.distinct.update(value)

    def values_snapshot(self, n: int):
        if self.spec.single_value:
            if self._fixed:
                return self._buf[:n].copy()
            return np.array(self._buf[:n], dtype=object)
        return self._values[:n]

    def null_bitmap(self, n: int) -> Optional[Bitmap]:
        nulls = [d for d in self._null_docs if d < n]
        if not nulls:
            return None
        return Bitmap.from_indices(n, nulls)


class _MutableClpColumn(_MutableColumn):
    """CLP-encoded mutable log column (ref the y-scope fork's realtime
    CLPMutableForwardIndex): rows append through segment/clp.py's
    encode_message into a growing logtype dictionary + variable stores,
    so the consuming segment holds templates and variables — not the
    raw message text. Queries decode per snapshot (consuming segments
    run host-side and are flush-capped small); sealing decodes once and
    SegmentCreator re-encodes into the immutable CLP forward index, so
    the seal->build->warm->swap pipeline rides unchanged.

    `distinct` is the logtype index dict: metadata cardinality reports
    TEMPLATE cardinality, the quantity that stays small and meaningful
    for log columns (raw-message distinct would defeat the encoding)."""

    def __init__(self, spec: FieldSpec):
        super().__init__(spec)
        self._logtypes: List[str] = []
        self._lt_index: Dict[str, int] = {}
        self._lt_ids: List[int] = []
        self._var_index: Dict[str, int] = {}
        self._var_ids: List[int] = []
        self._dv_counts: List[int] = []
        self._enc: List[int] = []
        self._enc_counts: List[int] = []
        self.distinct = self._lt_index

    def append(self, doc_id: int, value: Any) -> None:
        from pinot_tpu.segment.clp import encode_message
        spec = self.spec
        if value is None:
            self._null_docs.append(doc_id)
            value = spec.default_null_value
        lt, dv, ev = encode_message(str(value))
        lid = self._lt_index.get(lt)
        if lid is None:
            lid = len(self._logtypes)
            self._lt_index[lt] = lid
            self._logtypes.append(lt)
            self.nbytes_est += self._OBJ_OVERHEAD + len(lt)
        self._lt_ids.append(lid)
        for tok in dv:
            vid = self._var_index.get(tok)
            if vid is None:
                vid = len(self._var_index)
                self._var_index[tok] = vid
                self.nbytes_est += self._OBJ_OVERHEAD + len(tok)
            self._var_ids.append(vid)
        self._dv_counts.append(len(dv))
        self._enc.extend(ev)
        self._enc_counts.append(len(ev))
        # per-doc fixed cost: logtype id + var ids + encoded vars
        self.nbytes_est += 4 + 4 * len(dv) + 8 * len(ev)

    def values_snapshot(self, n: int):
        from pinot_tpu.segment.clp import decode_message
        vd = list(self._var_index)
        out = np.empty(n, dtype=object)
        di = ei = 0
        for d in range(n):
            ndv, nev = self._dv_counts[d], self._enc_counts[d]
            out[d] = decode_message(
                self._logtypes[self._lt_ids[d]],
                [vd[i] for i in self._var_ids[di:di + ndv]],
                self._enc[ei:ei + nev])
            di += ndv
            ei += nev
        return out


class _MutableDataSource:
    """Snapshot view implementing the DataSource duck type the executors
    consume (values + metadata; no sorted dict, no aux indexes)."""

    def __init__(self, col: _MutableColumn, n: int, meta: ColumnMetadata):
        self._col = col
        self._n = n
        self.metadata = meta

    def values(self) -> np.ndarray:
        return self._col.values_snapshot(self._n)

    def mv_offsets(self) -> np.ndarray:
        vals = self._col.values_snapshot(self._n)
        lens = np.array([len(v) for v in vals], dtype=np.int32)
        out = np.zeros(len(vals) + 1, dtype=np.int32)
        np.cumsum(lens, out=out[1:])
        return out

    def dict_ids(self):
        raise ValueError(f"mutable column {self.metadata.name} has no "
                         "sorted dictionary")

    @property
    def dictionary(self):
        return None

    @property
    def inverted_index(self):
        return None

    @property
    def json_index(self):
        return None  # json_match falls back to a transient per-query index

    @property
    def text_index(self):
        return None  # text_match likewise

    @property
    def range_index(self):
        return None

    @property
    def sorted_index(self):
        return None

    @property
    def bloom_filter(self):
        return None

    @property
    def null_value_vector(self) -> Optional[Bitmap]:
        return self._col.null_bitmap(self._n)


class MutableSegment:
    """Ref MutableSegmentImpl — the CONSUMING segment."""

    def __init__(self, segment_name: str, table_config: TableConfig,
                 schema: Schema):
        self.segment_name = segment_name
        self.table_config = table_config
        self.schema = schema
        clp_cols = set(getattr(table_config.indexing, "clp_columns",
                               None) or [])
        self._cols: Dict[str, _MutableColumn] = {
            s.name: (_MutableClpColumn(s)
                     if (s.name in clp_cols and s.single_value
                         and s.data_type == DataType.STRING)
                     else _MutableColumn(s))
            for s in schema.fields if not s.virtual}
        self._num_docs = 0
        self._lock = threading.Lock()
        self.start_consumption_time = time.time()

    # -- ingestion side -----------------------------------------------------
    def index(self, record: Dict[str, Any]) -> bool:
        """Append one transformed row (ref MutableSegmentImpl.index:515)."""
        with self._lock:
            doc_id = self._num_docs
            for name, col in self._cols.items():
                col.append(doc_id, record.get(name))
            self._num_docs += 1
        return True

    # -- query side (IndexSegment duck type) --------------------------------
    @property
    def name(self) -> str:
        return self.segment_name

    @property
    def num_docs(self) -> int:
        return self._num_docs

    @property
    def size_bytes(self) -> int:
        """Estimated indexed bytes across columns — the unit the
        ingestion backpressure budget (`pinot.server.ingest.memory.bytes`)
        meters against. An estimate, not a heap audit: fixed columns
        count itemsize per doc, variable values count length plus object
        overhead."""
        return sum(c.nbytes_est for c in self._cols.values())

    @property
    def column_names(self) -> List[str]:
        return list(self._cols.keys())

    def has_column(self, column: str) -> bool:
        return column in self._cols

    @property
    def metadata(self) -> SegmentMetadata:
        n = self._num_docs
        cols = {}
        for name, col in self._cols.items():
            cols[name] = self._col_meta(name, col, n)
        return SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.table_config.table_name_with_type,
            num_docs=n, columns=cols,
            time_column=self.table_config.retention.time_column)

    def _col_meta(self, name: str, col: _MutableColumn, n: int) -> ColumnMetadata:
        return ColumnMetadata(
            name=name, data_type=col.spec.data_type,
            field_type=col.spec.field_type,
            single_value=col.spec.single_value,
            has_dictionary=False,  # unsorted mutable dict -> value space
            cardinality=len(col.distinct), total_entries=n)

    def data_source(self, column: str) -> _MutableDataSource:
        return self.data_source_at(column, self._num_docs)

    def data_source_at(self, column: str, n: int) -> _MutableDataSource:
        """Data source bound to an EXPLICIT doc count — the snapshot()
        view pins one n for a whole query, so every column it reads has
        the same length even while the consumer appends."""
        col = self._cols.get(column)
        if col is None:
            raise KeyError(f"column {column!r} not in segment {self.segment_name}")
        return _MutableDataSource(col, n, self._col_meta(column, col, n))

    def snapshot(self) -> "_MutableSegmentSnapshot":
        """Consistent whole-query view: per-column data_source() calls
        each snapshot num_docs at CALL time, so a query reading several
        columns of a growing segment would see different lengths. The
        host executors take one snapshot per (segment, query) instead
        (ref: reference queries read up to one indexed row count)."""
        return _MutableSegmentSnapshot(self, self._num_docs)

    def destroy(self) -> None:
        self._cols.clear()

    # -- sealing ------------------------------------------------------------
    def to_columns(self) -> Dict[str, Any]:
        return self._to_columns(self._num_docs)

    def _to_columns(self, n: int) -> Dict[str, Any]:
        """Materialize all columns for immutable segment build."""
        out: Dict[str, Any] = {}
        for name, col in self._cols.items():
            vals = col.values_snapshot(n)
            nulls = col.null_bitmap(n)
            if nulls is not None and col.spec.single_value:
                vals = list(vals)
                for d in nulls.to_indices():
                    vals[d] = None
            out[name] = vals
        return out


class _MutableSegmentSnapshot:
    """Frozen-doc-count view of a consuming segment (IndexSegment duck
    type): every read resolves against ONE num_docs, so the host
    executors see length-consistent columns while the consumer appends.
    The validity bitmap is read live (upsert snapshot-per-query
    semantics) — the executor truncates/pads it to this view's n."""

    def __init__(self, seg: "MutableSegment", n: int):
        self._seg = seg
        self._n = n

    @property
    def name(self) -> str:
        return self._seg.segment_name

    @property
    def segment_name(self) -> str:
        return self._seg.segment_name

    @property
    def num_docs(self) -> int:
        return self._n

    @property
    def column_names(self) -> List[str]:
        return self._seg.column_names

    def has_column(self, column: str) -> bool:
        return self._seg.has_column(column)

    @property
    def metadata(self) -> SegmentMetadata:
        n = self._n
        seg = self._seg
        cols = {name: seg._col_meta(name, col, n)
                for name, col in seg._cols.items()}
        return SegmentMetadata(
            segment_name=seg.segment_name,
            table_name=seg.table_config.table_name_with_type,
            num_docs=n, columns=cols,
            time_column=seg.table_config.retention.time_column)

    def data_source(self, column: str) -> _MutableDataSource:
        return self._seg.data_source_at(column, self._n)

    @property
    def valid_doc_ids(self):
        return getattr(self._seg, "valid_doc_ids", None)
